#!/usr/bin/env python
"""Summarise a jax.profiler xplane trace: top ops by accumulated duration.

Usage: python scripts/analyze_xplane.py <dir-or-xplane.pb> [top_n]

Walks every plane/line in the XSpace (TPU device planes carry the XLA op
timeline; host planes carry runtime calls) and prints, per plane, the top
events by total duration with occurrence counts — enough to attribute a
decode step's time budget (BENCH_PROFILE=dir python bench.py writes the
trace this reads).

Parsing uses the raw XSpace protobuf via tensorflow's bundled schema — the
tensorboard_plugin_profile converters in this image are protobuf-version
broken, so this stays dependency-minimal on purpose.
"""

from __future__ import annotations

import os
import sys
from collections import defaultdict


def find_xplane_files(path: str) -> list[str]:
    if os.path.isfile(path):
        return [path]
    found = []
    for root, _, files in os.walk(path):
        for fname in files:
            if fname.endswith(".xplane.pb"):
                found.append(os.path.join(root, fname))
    return sorted(found)


def load_xspace(path: str):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    space = xplane_pb2.XSpace()
    with open(path, "rb") as f:
        space.ParseFromString(f.read())
    return space


def summarize(space, top_n: int = 25) -> None:
    for plane in space.planes:
        metadata = {m_id: m.name for m_id, m in plane.event_metadata.items()}
        # (line name, event name) -> [total_ps, count]
        totals: dict[tuple[str, str], list[float]] = defaultdict(lambda: [0.0, 0])
        line_totals: dict[str, float] = defaultdict(float)
        for line in plane.lines:
            lname = line.name or f"line-{line.id}"
            for event in line.events:
                name = metadata.get(event.metadata_id, str(event.metadata_id))
                entry = totals[(lname, name)]
                entry[0] += event.duration_ps
                entry[1] += 1
                line_totals[lname] += event.duration_ps
        if not totals:
            continue
        print(f"\n=== plane: {plane.name} ===")
        for lname, total_ps in sorted(line_totals.items(), key=lambda kv: -kv[1])[:6]:
            print(f"  line {lname}: {total_ps / 1e9:.3f} ms total")
        print(f"  top {top_n} events by accumulated duration:")
        ranked = sorted(totals.items(), key=lambda kv: -kv[1][0])[:top_n]
        for (lname, name), (ps, count) in ranked:
            print(f"    {ps / 1e9:9.3f} ms  x{count:<6} [{lname}] {name[:90]}")


def main() -> None:
    if len(sys.argv) < 2:
        sys.exit(__doc__)
    top_n = int(sys.argv[2]) if len(sys.argv) > 2 else 25
    files = find_xplane_files(sys.argv[1])
    if not files:
        sys.exit(f"no .xplane.pb under {sys.argv[1]}")
    for path in files:
        print(f"### {path}")
        summarize(load_xspace(path), top_n)


if __name__ == "__main__":
    main()
