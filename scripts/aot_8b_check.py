#!/usr/bin/env python
"""AOT-compile the Llama-3-8B int8 serving programs for v5e — no chip.

The north-star config (BASELINE.md: Llama-3-8B on a 16 GB v5e chip) has
never produced an on-chip number (VERDICT r4).  The CPU end-to-end run
(`RUN_8B_CPU=1`) proves the graph composes; THIS check makes the memory
claim chip-credible: the 8B int8 prefill and decode programs are lowered
and compiled against an abstract v5e topology, and the XLA compiler's own
memory analysis (argument/output/temp bytes) is reported against the
16 GB HBM budget.  `jax.eval_shape` supplies the quantized parameter and
KV-cache trees as shapes only — nothing is materialised.

Prints one JSON line; exit 1 on compile failure or budget overflow, 42
when this jax install has no TPU compiler (skip sentinel, matching
scripts/aot_tpu_check.py).
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from operator_tpu.utils.platform import pin_cpu_if_requested  # noqa: E402

pin_cpu_if_requested()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

HBM_BYTES = 16e9  # v5e chip
SLOTS, MAX_SEQ = 8, 2048  # the bench_8b shape (scripts/tpu_experiments.sh)


def _size(tree) -> int:
    return sum(
        math.prod(x.shape) * x.dtype.itemsize for x in jax.tree.leaves(tree)
    )


def main() -> int:
    from jax.experimental import topologies
    from jax.sharding import SingleDeviceSharding

    try:
        topo = topologies.get_topology_desc(
            platform="tpu", topology_name="v5e:2x2x1"
        )
    except Exception as exc:
        if os.environ.get("AOT_TPU_TOPOLOGY"):
            raise
        print(f"SKIP: no TPU topology support here ({exc})", file=sys.stderr)
        return 42
    sharding = SingleDeviceSharding(topo.devices[0])

    from operator_tpu.models.configs import LLAMA_3_8B
    from operator_tpu.models.llama import KVCache, forward, init_params
    from operator_tpu.models.quant import quantize_params

    config = dataclasses.replace(LLAMA_3_8B, max_seq_len=MAX_SEQ)

    def place(tree):
        return jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding),
            tree,
        )

    params = place(jax.eval_shape(
        lambda key: quantize_params(
            init_params(config, key, dtype=jnp.bfloat16), config
        ),
        jax.random.PRNGKey(0),
    ))
    cache = place(jax.eval_shape(
        lambda: KVCache.create(config, SLOTS, MAX_SEQ, dtype=jnp.bfloat16)
    ))

    def shaped(shape, dtype):
        return jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)

    def prefill(params, cache, ids, lengths):
        positions = jnp.broadcast_to(
            jnp.arange(MAX_SEQ, dtype=jnp.int32)[None], (SLOTS, MAX_SEQ)
        )
        kv_valid = positions < lengths[:, None]
        logits, cache = forward(
            params, config, ids, positions, cache=cache, cache_offset=0,
            kv_valid=kv_valid, prefill_lengths=lengths,
        )
        return logits[:, -1, :], cache

    def decode(params, cache, tokens, offsets):
        logits, cache = forward(
            params, config, tokens, offsets[:, None], cache=cache,
            cache_offset=offsets,
        )
        return jnp.argmax(logits[:, -1, :], axis=-1), cache

    record = {
        "metric": "aot_8b_v5e",
        "model": config.name,
        "slots": SLOTS,
        "max_seq": MAX_SEQ,
        "weights_int8_gb": round(_size(params) / 1e9, 2),
        "kv_cache_gb": round(_size(cache) / 1e9, 2),
        "hbm_budget_gb": HBM_BYTES / 1e9,
        "programs": {},
    }
    failed = 0
    cases = [
        # decode first: the latency-critical program, and the cheaper
        # compile — a timeboxed run records it even if prefill's larger
        # graph exhausts the window
        ("decode_8", decode, (
            params, cache,
            shaped((SLOTS, 1), jnp.int32), shaped((SLOTS,), jnp.int32),
        )),
        ("prefill_8x2048", prefill, (
            params, cache,
            shaped((SLOTS, MAX_SEQ), jnp.int32), shaped((SLOTS,), jnp.int32),
        )),
    ]
    for name, fn, args in cases:
        try:
            compiled = jax.jit(fn).lower(*args).compile()
            entry = {"ok": True}
            try:
                mem = compiled.memory_analysis()
                arg_b = int(getattr(mem, "argument_size_in_bytes", 0))
                out_b = int(getattr(mem, "output_size_in_bytes", 0))
                tmp_b = int(getattr(mem, "temp_size_in_bytes", 0))
                alias_b = int(getattr(mem, "alias_size_in_bytes", 0))
                # peak live bytes: arguments + outputs + temporaries minus
                # buffers XLA aliases between args and outputs (the cache)
                peak = arg_b + out_b + tmp_b - alias_b
                entry.update({
                    "argument_gb": round(arg_b / 1e9, 2),
                    "output_gb": round(out_b / 1e9, 2),
                    "temp_gb": round(tmp_b / 1e9, 2),
                    "aliased_gb": round(alias_b / 1e9, 2),
                    "peak_gb": round(peak / 1e9, 2),
                    "fits_16gb": bool(peak < HBM_BYTES),
                })
                if peak >= HBM_BYTES:
                    failed += 1
            except Exception as exc:  # noqa: BLE001 - stats best-effort
                entry["memory_analysis_error"] = str(exc)[:120]
            record["programs"][name] = entry
            print(f"OK   {name}: {entry}", file=sys.stderr)
        except Exception as exc:  # noqa: BLE001 - record and continue
            failed += 1
            record["programs"][name] = {
                "ok": False, "error": f"{type(exc).__name__}: {exc}"[:300],
            }
            print(f"FAIL {name}: {exc}", file=sys.stderr)
    record["failed"] = failed
    print(json.dumps(record))
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
