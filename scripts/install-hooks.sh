#!/usr/bin/env bash
# Install the repo's git hooks: a pre-commit graftlint pass over exactly
# the files you changed (`--changed-only HEAD`), so findings surface in
# seconds at commit time instead of minutes later in CI.
#
#   bash scripts/install-hooks.sh
#
# The hook runs the full 13-rule catalogue but parses only the changed
# files (repo-level artifact rules still check the whole tree — see
# docs/ANALYSIS.md "Running locally").  Bypass for a work-in-progress
# commit with `git commit --no-verify`; CI remains the hard gate.
set -euo pipefail

root="$(git rev-parse --show-toplevel)"
hooks_dir="$(git -C "$root" rev-parse --git-path hooks)"
mkdir -p "$hooks_dir"

hook="$hooks_dir/pre-commit"
if [ -e "$hook" ] && ! grep -q "operator_tpu.analysis" "$hook"; then
    echo "refusing to overwrite existing non-graftlint hook: $hook" >&2
    echo "append 'python -m operator_tpu.analysis --changed-only HEAD' to it yourself" >&2
    exit 1
fi

cat > "$hook" <<'HOOK'
#!/usr/bin/env bash
# graftlint pre-commit (installed by scripts/install-hooks.sh):
# lint the changed files against the committed baseline before CI does.
set -euo pipefail
cd "$(git rev-parse --show-toplevel)"
exec python -m operator_tpu.analysis \
    --baseline analysis-baseline.json \
    --changed-only HEAD
HOOK
chmod +x "$hook"
echo "installed graftlint pre-commit hook: $hook"
