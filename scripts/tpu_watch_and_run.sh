#!/bin/bash
# Recovery watcher: poll until the TPU tunnel answers, then run the full
# experiment series once.  Survives tunnel outages that outlast any single
# step's wait window (scripts/tpu_experiments.sh aborts fast on a dead
# tunnel; this relaunches it when the chip returns).  The series commits
# docs/R5_RESULTS.md after every completed step, so this wrapper only
# needs to relaunch on rc=2 (mid-series tunnel death).
set -u
OUT=$(realpath -m "${1:-$(cd "$(dirname "$0")/.." && pwd)/r5_experiments}")
cd "$(dirname "$0")/.."
mkdir -p "$OUT"
echo "watcher start $(date +%H:%M:%S)" >> "$OUT/watcher.log"
while true; do
  if timeout 90 python -c "import jax; assert jax.devices()[0].platform == 'tpu'" \
      > /dev/null 2>&1; then
    echo "chip up $(date +%H:%M:%S); launching series" >> "$OUT/watcher.log"
    bash scripts/tpu_experiments.sh "$OUT"
    rc=$?
    echo "series rc=$rc $(date +%H:%M:%S)" >> "$OUT/watcher.log"
    # belt-and-braces final capture: covers a series killed between a
    # step's run and its own capture call
    python scripts/summarize_series.py "$OUT" docs/R5_RESULTS.md \
        >> "$OUT/watcher.log" 2>&1
    if [ -f docs/R5_RESULTS.md ] && { \
        ! git ls-files --error-unmatch docs/R5_RESULTS.md > /dev/null 2>&1 \
        || ! git diff --quiet HEAD -- docs/R5_RESULTS.md 2>/dev/null; }; then
      git add docs/R5_RESULTS.md 2>/dev/null
      git commit -m "Record on-chip experiment series results" \
          -- docs/R5_RESULTS.md >> "$OUT/watcher.log" 2>&1
    fi
    # rc=2 means the tunnel died mid-series: go back to polling and rerun
    [ "$rc" != 2 ] && break
  else
    echo "chip down $(date +%H:%M:%S)" >> "$OUT/watcher.log"
    sleep 120
  fi
done
echo "watcher done $(date +%H:%M:%S)" >> "$OUT/watcher.log"
