#!/usr/bin/env python
"""AOT-compile every Pallas kernel for a real v5e target — no chip needed.

VERDICT r4 item 3: the v2 paged kernel and flash prefill had never lowered
for physical TPU; Mosaic lowering failures (layout/window asserts) surface
at COMPILE time, so cross-compiling against an abstract v5e topology
(`jax.experimental.topologies`) on the CPU host validates exactly that
risk without burning a tunnel window.  Runtime parity still needs the
chip (scripts/tpu_kernel_smoke.py, first step of the experiment series);
this check de-risks it.

Prints one line per (kernel, dtype) and a final JSON summary; exits 1 on
any failure, 42 when the jax install has no TPU compiler (plain CI
wheels) — callers treat 42 (and only 42: CPython itself exits 2 on a
missing script) as skip.
"""

from __future__ import annotations

import json
import sys

# never let the default-backend probe touch a (possibly wedged) tunnel
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from operator_tpu.utils.platform import pin_cpu_if_requested  # noqa: E402

pin_cpu_if_requested()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

TOPOLOGY = os.environ.get("AOT_TPU_TOPOLOGY", "v5e:2x2x1")


def main() -> int:
    from jax.experimental import topologies
    from jax.sharding import SingleDeviceSharding

    try:
        topo = topologies.get_topology_desc(
            platform="tpu", topology_name=TOPOLOGY
        )
    except Exception as exc:
        if os.environ.get("AOT_TPU_TOPOLOGY"):
            # an explicitly requested topology failing is an ERROR, not a
            # missing-compiler skip — surfacing typos/format drift
            raise
        print(f"SKIP: no TPU topology support here ({exc})", file=sys.stderr)
        return 42
    sharding = SingleDeviceSharding(topo.devices[0])

    def shaped(shape, dtype):
        return jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)

    from operator_tpu.ops.flash_prefill import _flash_prefill_pallas
    from operator_tpu.ops.paged_attention import (
        _paged_attention_pallas,
        _paged_attention_pallas_v2,
    )
    from operator_tpu.ops.similarity import _best_window_pallas

    b, qh, kh, d, page, pps = 4, 32, 8, 128, 16, 8
    fb, ft = 2, 256

    def paged_args(dtype):
        return (
            shaped((b, qh, d), dtype),
            shaped((b * pps, page, kh, d), dtype),
            shaped((b * pps, page, kh, d), dtype),
            shaped((b, pps), jnp.int32),
            shaped((b,), jnp.int32),
        )

    def flash_args(dtype):
        return (
            shaped((fb, ft, qh, d), dtype),
            shaped((fb, ft, kh, d), dtype),
            shaped((fb, ft, kh, d), dtype),
            shaped((fb,), jnp.int32),
        )

    import functools

    cases = [
        ("similarity_best_window", _best_window_pallas,
         (shaped((1000, 384), jnp.float32), shaped((300, 384), jnp.float32))),
    ]
    for dtype, tag in ((jnp.float32, "f32"), (jnp.bfloat16, "bf16")):
        cases.append((f"paged_attention_v1_{tag}",
                      _paged_attention_pallas, paged_args(dtype)))
        cases.append((f"paged_attention_v2_{tag}",
                      _paged_attention_pallas_v2, paged_args(dtype)))
        cases.append((f"flash_prefill_{tag}",
                      _flash_prefill_pallas, flash_args(dtype)))
    # the windowed variants lower DIFFERENT Mosaic code (first-block
    # computation + extra mask term): sliding-window models would hit
    # them first on-chip otherwise
    cases.append((
        "paged_attention_v2_bf16_window",
        functools.partial(_paged_attention_pallas_v2, sliding_window=64),
        paged_args(jnp.bfloat16),
    ))
    cases.append((
        "flash_prefill_bf16_window",
        functools.partial(_flash_prefill_pallas, sliding_window=128),
        flash_args(jnp.bfloat16),
    ))

    results, failed = {}, 0
    for name, fn, args in cases:
        try:
            compiled = jax.jit(fn).lower(*args).compile()
            stats = {}
            try:
                mem = compiled.memory_analysis()
                if mem is not None:
                    stats["temp_bytes"] = int(
                        getattr(mem, "temp_size_in_bytes", 0)
                    )
            except Exception:  # noqa: BLE001 - stats are best-effort
                pass
            results[name] = {"ok": True, **stats}
            print(f"OK   {name}", file=sys.stderr)
        except Exception as exc:  # noqa: BLE001 - record and continue
            failed += 1
            results[name] = {"ok": False, "error": f"{type(exc).__name__}: {exc}"[:300]}
            print(f"FAIL {name}: {exc}", file=sys.stderr)
    print(json.dumps({
        "metric": "aot_tpu_kernel_compile",
        "topology": TOPOLOGY,
        "kernels": results,
        "failed": failed,
    }))
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
