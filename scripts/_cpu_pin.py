"""Shared CPU-pin shim for standalone scripts.

Thin re-export of :mod:`operator_tpu.utils.platform` (see its docstring
for why the env var alone is not enough) so scripts that only have the
scripts/ directory on ``sys.path`` can import it before any jax use.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from operator_tpu.utils.platform import pin_cpu_if_requested  # noqa: F401
