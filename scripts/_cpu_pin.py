"""Shared CPU-pin shim for standalone scripts.

The container sitecustomize force-registers the axon TPU plugin in every
python process and sets ``jax_platforms="axon,cpu"``, so the env var
``JAX_PLATFORMS=cpu`` alone does NOT stop ``jax.devices()`` from probing
the tunnel — and a dead/claimed tunnel hangs the probe with no output.
Import this module (or call :func:`pin_cpu_if_requested`) BEFORE any jax
backend query; it pins the cpu platform via ``jax.config`` when the
caller asked for cpu.  One shared site so the workaround cannot drift
between scripts (tests/conftest.py and __graft_entry__.py carry the same
pattern for their own import-order reasons).
"""

from __future__ import annotations

import os


def pin_cpu_if_requested(force: bool = False) -> bool:
    """Pin jax to the cpu platform when requested; returns True if pinned.

    ``force=True`` pins unconditionally (for smoke modes that must never
    touch the tunnel even when the env var is unset).
    """
    if force or os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
        return True
    return False
