#!/usr/bin/env python
"""Cold-vs-warm AOT-cache smoke (CI chaos job; CPU, tiny-test model).

Boots a generator with an AOT cache directory, drives the warmup grid
(cold boot: compiles + persists), tears the generator down, boots a fresh
one against the same directory, and asserts the warm boot

- performed ZERO serving-program compiles (CompileWatcher events filtered
  through serving/aotcache.py SERVING_PROGRAM_MARKERS — the strict
  in-process assertion is empty-event-list, since fresh jit closures would
  otherwise recompile every serving program),
- restored executables from the cache (hits > 0, live_compiles == 0), and
- was strictly faster than the cold boot.

Exit code 0 on success; prints a one-line JSON verdict either way.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from operator_tpu.models import TINY_TEST, init_params  # noqa: E402
from operator_tpu.models.tokenizer import ByteTokenizer  # noqa: E402
from operator_tpu.serving.aotcache import serving_compile_events  # noqa: E402
from operator_tpu.serving.engine import BatchedGenerator  # noqa: E402
from operator_tpu.utils.compilewatch import CompileWatcher  # noqa: E402


def boot(params, cache_dir: str) -> tuple:
    """One bring-up: generator + warmup grid; returns (seconds, aot stats)."""
    started = time.perf_counter()
    generator = BatchedGenerator(
        params, TINY_TEST, ByteTokenizer(), max_slots=2, max_seq=128,
        cache_dtype=jnp.float32, paged=True, page_size=16, decode_block=2,
        aot_cache=cache_dir,
    )
    generator.precompile_grid("serving")
    seconds = time.perf_counter() - started
    stats = generator._aot.stats()
    return seconds, stats


def main() -> int:
    params = init_params(TINY_TEST, jax.random.PRNGKey(0), dtype=jnp.float32)
    watcher = CompileWatcher()
    with tempfile.TemporaryDirectory(prefix="aot-smoke-") as cache_dir:
        cold_s, cold = boot(params, cache_dir)
        assert cold["stored"] > 0, f"cold boot persisted nothing: {cold}"

        watcher.mark()
        warm_s, warm = boot(params, cache_dir)
        serving_events = serving_compile_events(watcher.events_since_mark())

        verdict = {
            "cold_s": round(cold_s, 2),
            "warm_s": round(warm_s, 2),
            "cold": cold,
            "warm": warm,
            "warm_serving_compiles": [e[1] for e in serving_events],
        }
        failures = []
        if serving_events:
            failures.append(
                f"warm boot compiled serving programs: {[e[1] for e in serving_events]}"
            )
        if warm["live_compiles"] != 0:
            failures.append(f"warm live_compiles={warm['live_compiles']} != 0")
        if warm["hits"] == 0:
            failures.append("warm boot restored nothing from the cache")
        if warm_s >= cold_s:
            failures.append(f"warm boot {warm_s:.2f}s not faster than cold {cold_s:.2f}s")
        verdict["ok"] = not failures
        verdict["failures"] = failures
        print(json.dumps(verdict))
        return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
