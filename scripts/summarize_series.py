#!/usr/bin/env python
"""Summarize a tpu_experiments.sh output directory into markdown.

Reads every ``bench_*.log`` (the JSON line bench.py prints), the floor
and attribution logs, and writes a comparison table — the round's
evidence in one place (``docs/R5_RESULTS.md`` when run after each series step).  No jax import; safe to run anywhere.
"""

from __future__ import annotations

import json
import os
import re
import sys


def bench_rows(out_dir: str) -> list[tuple[str, dict]]:
    rows = []
    for name in sorted(os.listdir(out_dir)):
        if not (name.startswith("bench_") and name.endswith(".log")):
            continue
        record = None
        with open(os.path.join(out_dir, name), errors="replace") as f:
            for line in f:
                line = line.strip()
                if line.startswith("{") and '"metric"' in line:
                    try:
                        record = json.loads(line)
                    except json.JSONDecodeError:
                        continue
        if record:
            rows.append((name[len("bench_"):-len(".log")], record))
    return rows


def grep(path: str, pattern: str, limit: int = 12) -> list[str]:
    if not os.path.exists(path):
        return []
    matches = []
    with open(path, errors="replace") as f:
        for line in f:
            if re.search(pattern, line):
                matches.append(line.rstrip())
                if len(matches) >= limit:
                    break
    return matches


def main() -> None:
    out_dir = sys.argv[1] if len(sys.argv) > 1 else "/root/r4_experiments"
    target = sys.argv[2] if len(sys.argv) > 2 else "-"

    lines = ["# Round-4 on-chip experiment results", ""]
    series = os.path.join(out_dir, "series.log")
    if os.path.exists(series):
        lines += ["## Series timeline", "", "```"]
        lines += [l.rstrip() for l in open(series, errors="replace")][-40:]
        lines += ["```", ""]

    floors = grep(os.path.join(out_dir, "floor.log"),
                  r"HBM|MXU|stream floor|device:")
    if floors:
        lines += ["## Hardware floors", "", "```", *floors, "```", ""]

    attr = grep(os.path.join(out_dir, "decode_attr.log"),
                r"ms/step|device:|block=")
    if attr:
        lines += ["## Decode attribution", "", "```", *attr, "```", ""]

    rows = bench_rows(out_dir)
    if rows:
        lines += [
            "## Bench comparison rows", "",
            "| variant | expl/min | tok/s | p50 s | p99 s | open-loop p50@rate | model | dtype | notes |",
            "|---|---|---|---|---|---|---|---|---|",
        ]
        for variant, r in rows:
            open_loop = r.get("open_loop") or []
            ol = (
                f"{open_loop[0].get('p50_s')}s @{open_loop[0].get('rate_per_min')}/min"
                if open_loop else "-"
            )
            notes = []
            if r.get("degraded"):
                notes.append("DEGRADED (cpu fallback)")
            if r.get("error"):
                notes.append(f"error: {r['error'][:60]}")
            lines.append(
                f"| {variant} | {r.get('value')} | {r.get('decode_tokens_per_s')} "
                f"| {r.get('p50_latency_s')} | {r.get('p99_latency_s')} | {ol} "
                f"| {r.get('model')} | {r.get('weight_dtype')} "
                f"| {' '.join(notes) or '-'} |"
            )
        lines.append("")

    trace = grep(os.path.join(out_dir, "trace_summary.log"), r"\S", limit=40)
    if trace:
        lines += ["## xplane top ops", "", "```", *trace, "```", ""]

    text = "\n".join(lines) + "\n"
    if target == "-":
        print(text)
    else:
        with open(target, "w") as f:
            f.write(text)
        print(f"wrote {target}")


if __name__ == "__main__":
    main()
