#!/usr/bin/env python
"""Train the shipped log-BPE vocab (operator_tpu/models/bpe_vocab/).

Corpus: recorded failure fixtures, the builtin pattern library text, repo
prose (README/SURVEY), and the serving prompt template rendered over every
fixture — the text the production tokenizer actually sees.  Re-run after
growing the corpus:  python scripts/train_bpe.py [vocab_size]
"""

from __future__ import annotations

import glob
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from operator_tpu.models.bpe import BPETokenizer, BUILTIN_VOCAB, train_bpe

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def corpus() -> list[str]:
    texts: list[str] = []
    for pattern in ("tests/fixtures/*.log", "*.md", "operator_tpu/patterns/builtin/*.yaml"):
        for path in sorted(glob.glob(os.path.join(REPO, pattern))):
            with open(path, errors="replace") as f:
                texts.append(f.read())
    # the prompt template rendered over the real fixtures — the exact text
    # the serving engine tokenizes
    from operator_tpu.patterns.engine import PatternEngine
    from operator_tpu.schema.analysis import AnalysisRequest, PodFailureData
    from operator_tpu.serving.prompts import build_prompt

    engine = PatternEngine()
    for path in sorted(glob.glob(os.path.join(REPO, "tests/fixtures/*.log"))):
        with open(path) as f:
            failure = PodFailureData(logs=f.read())
        result = engine.analyze(failure)
        texts.append(build_prompt(AnalysisRequest(analysis_result=result,
                                                  failure_data=failure)))
    return texts


def main() -> None:
    vocab_size = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
    texts = corpus()
    total = sum(len(t) for t in texts)
    print(f"corpus: {len(texts)} documents, {total/1e3:.0f} kB")
    merges = train_bpe(texts, vocab_size)
    tok = BPETokenizer(merges)
    tok.save(BUILTIN_VOCAB)
    held_out = texts[0]
    ids = tok.encode(held_out)
    print(f"trained {len(merges)} merges -> vocab {tok.vocab_size}")
    print(f"compression on corpus[0]: {len(held_out)/max(1,len(ids)):.2f} chars/token")
    print(f"wrote {BUILTIN_VOCAB} ({os.path.getsize(BUILTIN_VOCAB)/1e3:.0f} kB)")


if __name__ == "__main__":
    main()
