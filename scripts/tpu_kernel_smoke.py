"""Real-chip smoke test: compile + parity of all four Pallas kernels on TPU.

Runs FIRST in scripts/tpu_experiments.sh (kernels-first ordering): a
short tunnel window (15-minute timebox) validates Mosaic lowering of the
exact kernels the perf series depends on before any long bench spends
chip time.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import jax, jax.numpy as jnp

from operator_tpu.ops.similarity import _best_window_pallas, best_window_scores_reference
from operator_tpu.ops.paged_attention import (
    _paged_attention_pallas,
    _paged_attention_pallas_v2,
    paged_attention_reference,
)
from operator_tpu.ops.flash_prefill import _flash_prefill_pallas, flash_prefill_reference

dev = jax.devices()[0]
print("device:", dev, dev.platform)

key = jax.random.PRNGKey(0)
w = jax.device_put(jax.random.normal(key, (1000, 384), jnp.float32), dev)
w = w / jnp.linalg.norm(w, axis=-1, keepdims=True)
p = jax.device_put(jax.random.normal(jax.random.PRNGKey(1), (300, 384), jnp.float32), dev)
p = p / jnp.linalg.norm(p, axis=-1, keepdims=True)
s_k, i_k = _best_window_pallas(w, p)
s_r, i_r = best_window_scores_reference(w, p)
np.testing.assert_allclose(np.asarray(s_k), np.asarray(s_r), atol=1e-4)
print("similarity kernel: OK, max |d| =", float(jnp.max(jnp.abs(s_k - s_r))))

b, qh, kh, d, page, pps = 4, 32, 8, 128, 16, 8
q = jax.device_put(jax.random.normal(jax.random.PRNGKey(2), (b, qh, d), jnp.float32), dev)
kp = jax.device_put(jax.random.normal(jax.random.PRNGKey(3), (b*pps, page, kh, d), jnp.float32), dev)
vp = jax.device_put(jax.random.normal(jax.random.PRNGKey(4), (b*pps, page, kh, d), jnp.float32), dev)
table = jax.device_put(jnp.arange(b*pps, dtype=jnp.int32).reshape(b, pps), dev)
lens = jax.device_put(jnp.asarray([5, 77, 128, 33], jnp.int32), dev)
o_k = _paged_attention_pallas(q, kp, vp, table, lens)
o_r = paged_attention_reference(q, kp, vp, table, lens)
# default MXU f32 precision: kernel vs XLA reference agree to ~1e-2 on TPU
# (XLA's own TPU-vs-CPU gap is the same magnitude)
np.testing.assert_allclose(np.asarray(o_k), np.asarray(o_r), atol=2e-2)
print("paged attention kernel: OK, max |d| =", float(jnp.max(jnp.abs(o_k - o_r))))

o_k2 = _paged_attention_pallas_v2(q, kp, vp, table, lens)
np.testing.assert_allclose(np.asarray(o_k2), np.asarray(o_r), atol=2e-2)
print("paged attention kernel v2: OK, max |d| =", float(jnp.max(jnp.abs(o_k2 - o_r))))

fb, ft, fqh, fkh, fd = 2, 256, 32, 8, 128
fq = jax.device_put(jax.random.normal(jax.random.PRNGKey(5), (fb, ft, fqh, fd), jnp.float32), dev)
fk = jax.device_put(jax.random.normal(jax.random.PRNGKey(6), (fb, ft, fkh, fd), jnp.float32), dev)
fv = jax.device_put(jax.random.normal(jax.random.PRNGKey(7), (fb, ft, fkh, fd), jnp.float32), dev)
flens = jax.device_put(jnp.asarray([256, 131], jnp.int32), dev)
f_k = _flash_prefill_pallas(fq, fk, fv, flens)
f_r = flash_prefill_reference(fq, fk, fv, flens)
np.testing.assert_allclose(np.asarray(f_k), np.asarray(f_r), atol=2e-2)
print("flash prefill kernel: OK, max |d| =", float(jnp.max(jnp.abs(f_k - f_r))))
