"""Real-chip smoke test: compile + parity of both Pallas kernels on TPU."""
import numpy as np
import jax, jax.numpy as jnp

from operator_tpu.ops.similarity import _best_window_pallas, best_window_scores_reference
from operator_tpu.ops.paged_attention import _paged_attention_pallas, paged_attention_reference

dev = jax.devices()[0]
print("device:", dev, dev.platform)

key = jax.random.PRNGKey(0)
w = jax.device_put(jax.random.normal(key, (1000, 384), jnp.float32), dev)
w = w / jnp.linalg.norm(w, axis=-1, keepdims=True)
p = jax.device_put(jax.random.normal(jax.random.PRNGKey(1), (300, 384), jnp.float32), dev)
p = p / jnp.linalg.norm(p, axis=-1, keepdims=True)
s_k, i_k = _best_window_pallas(w, p)
s_r, i_r = best_window_scores_reference(w, p)
np.testing.assert_allclose(np.asarray(s_k), np.asarray(s_r), atol=1e-4)
print("similarity kernel: OK, max |d| =", float(jnp.max(jnp.abs(s_k - s_r))))

b, qh, kh, d, page, pps = 4, 32, 8, 128, 16, 8
q = jax.device_put(jax.random.normal(jax.random.PRNGKey(2), (b, qh, d), jnp.float32), dev)
kp = jax.device_put(jax.random.normal(jax.random.PRNGKey(3), (b*pps, page, kh, d), jnp.float32), dev)
vp = jax.device_put(jax.random.normal(jax.random.PRNGKey(4), (b*pps, page, kh, d), jnp.float32), dev)
table = jax.device_put(jnp.arange(b*pps, dtype=jnp.int32).reshape(b, pps), dev)
lens = jax.device_put(jnp.asarray([5, 77, 128, 33], jnp.int32), dev)
o_k = _paged_attention_pallas(q, kp, vp, table, lens)
o_r = paged_attention_reference(q, kp, vp, table, lens)
# default MXU f32 precision: kernel vs XLA reference agree to ~1e-2 on TPU
# (XLA's own TPU-vs-CPU gap is the same magnitude)
np.testing.assert_allclose(np.asarray(o_k), np.asarray(o_r), atol=2e-2)
print("paged attention kernel: OK, max |d| =", float(jnp.max(jnp.abs(o_k - o_r))))
