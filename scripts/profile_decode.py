#!/usr/bin/env python
"""Decode attribution at engine-block granularity (the real dispatch unit).

Times BatchedGenerator.step() — one lax.scan block of decode_block steps,
one host token fetch — under one-variable-at-a-time toggles:

  paged vs contiguous | sampler: topp/topk/greedy | donate cache or not

Env: PD_BLOCK (8), PD_SLOTS (16), PD_SEQ (1024), PD_STEPS (12 blocks).
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _cpu_pin import pin_cpu_if_requested

pin_cpu_if_requested()

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

from operator_tpu.models import get_config, init_params
from operator_tpu.models.tokenizer import load_tokenizer
from operator_tpu.serving.engine import BatchedGenerator, SamplingParams

BLOCK = int(os.environ.get("PD_BLOCK", "8"))
SLOTS = int(os.environ.get("PD_SLOTS", "16"))
SEQ = int(os.environ.get("PD_SEQ", "1024"))
STEPS = int(os.environ.get("PD_STEPS", "12"))


def measure(params, config, *, paged, sampler, donate, block=BLOCK):
    gen = BatchedGenerator(
        params, config, load_tokenizer(None), max_slots=SLOTS, max_seq=SEQ,
        paged=paged, page_size=64, decode_block=block,
    )
    if sampler == "greedy":
        def greedy(logits, rng, temp, top_p):
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), rng
        gen._sample = greedy
    elif sampler == "fullsort":
        # the pre-r3 sampler: full-vocab sort every step (what the engine
        # shipped before truncated top-k; kept here so the trade stays
        # measurable against sampler == "default")
        def fullsort(logits, rng, temp, top_p):
            vocab = logits.shape[-1]
            greedy_t = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            safe_temp = jnp.maximum(temp, 1e-4)[:, None]
            scaled = logits.astype(jnp.float32) / safe_temp
            sorted_logits, sorted_idx = jax.lax.top_k(scaled, vocab)
            probs = jax.nn.softmax(sorted_logits, axis=-1)
            cumulative = jnp.cumsum(probs, axis=-1) - probs
            keep = cumulative < top_p[:, None]
            filtered = jnp.where(keep, sorted_logits, -jnp.inf)
            rng, sub = jax.random.split(rng)
            choice = jax.random.categorical(sub, filtered, axis=-1)
            sampled = jnp.take_along_axis(sorted_idx, choice[:, None], axis=-1)[:, 0]
            return jnp.where(temp <= 0.0, greedy_t, sampled.astype(jnp.int32)), rng
        gen._sample = fullsort
    else:
        assert sampler == "default"  # engine's truncated top-k nucleus
    if donate:
        # re-jit the decode fn with cache donation (arg 1 in both layouts)
        fn = gen._decode_block_paged if paged else gen._decode_block
        gen._decode_fn = jax.jit(fn, donate_argnums=(1,))

    prompts = ["pod failed with exit code 137 " * 8] * SLOTS
    sampling = SamplingParams(max_tokens=BLOCK * (STEPS + 6), temperature=0.3,
                              stop_on_eos=False)
    gen.admit(prompts, [sampling] * SLOTS)
    # warm the decode program
    for _ in range(3):
        gen.step()
    t0 = time.perf_counter()
    for _ in range(STEPS):
        gen.step()
    dt = time.perf_counter() - t0
    ms_per_step = dt / (STEPS * block) * 1e3
    toks = SLOTS * STEPS * block / dt
    return ms_per_step, toks


def main():
    print(f"device: {jax.devices()[0]}  block={BLOCK} slots={SLOTS} seq={SEQ}",
          flush=True)
    config = get_config(os.environ.get("PD_MODEL", "tinyllama-1.1b"))
    params = jax.block_until_ready(
        jax.jit(lambda k: init_params(config, k, dtype=jnp.bfloat16))(
            jax.random.PRNGKey(0)
        )
    )

    cases = [
        dict(paged=True, sampler="default", donate=False),   # shipped config
        dict(paged=True, sampler="fullsort", donate=False),  # pre-r3 sampler
        dict(paged=True, sampler="greedy", donate=False),
        dict(paged=True, sampler="greedy", donate=True),
        dict(paged=False, sampler="default", donate=False),
        dict(paged=False, sampler="greedy", donate=False),
        dict(paged=False, sampler="greedy", donate=True),
        dict(paged=False, sampler="default", donate=True),
        dict(paged=True, sampler="default", donate=True),
    ]
    for case in cases:
        ms, toks = measure(params, config, **case)
        print(f"paged={case['paged']!s:5} sampler={case['sampler']:6} "
              f"donate={case['donate']!s:5} -> {ms:6.2f} ms/step  {toks:7.0f} tok/s",
              flush=True)


if __name__ == "__main__":
    main()
