#!/bin/bash
# One-command end-to-end run against a REAL Kubernetes apiserver
# (VERDICT r4 item 5): every operator test in the suite runs against the
# in-repo fake (operator/kubeapi.py); this script validates the hand-rolled
# HTTP client — merge-patch semantics, watch line framing + resourceVersion
# resume, status subresource writes, RBAC and CRD schema correctness —
# against the thing the reference actually runs on (fabric8 client,
# reference PodFailureWatcher.java:92).
#
# Requires: kind (or an existing cluster via KUBECONFIG + E2E_SKIP_KIND=1),
# kubectl, and network to pull the busybox image for the crashing pod.
# Not runnable in the offline build image — run it on a workstation/CI:
#
#   bash scripts/e2e_kind.sh            # create kind cluster, test, delete
#   E2E_KEEP=1 bash scripts/e2e_kind.sh # keep the cluster for inspection
#   E2E_SKIP_KIND=1 KUBECONFIG=... bash scripts/e2e_kind.sh  # your cluster
set -euo pipefail
cd "$(dirname "$0")/.."

CLUSTER=${E2E_CLUSTER_NAME:-podmortem-e2e}

if [ "${E2E_SKIP_KIND:-0}" != "1" ]; then
  command -v kind >/dev/null || { echo "kind not found (https://kind.sigs.k8s.io)"; exit 2; }
  kind create cluster --name "$CLUSTER" --wait 120s
  trap '[ "${E2E_KEEP:-0}" = "1" ] || kind delete cluster --name "$CLUSTER"' EXIT
  kind export kubeconfig --name "$CLUSTER"
fi
command -v kubectl >/dev/null || { echo "kubectl not found"; exit 2; }

# the operator's own API surface: CRDs + namespace + RBAC, exactly what a
# production install applies (deploy/); the operator process itself runs
# OUT of cluster against the kubeconfig, so the Deployment is not applied
kubectl apply -f deploy/crds/podmortem-crds.yaml
kubectl create namespace podmortem-system --dry-run=client -o yaml | kubectl apply -f -
kubectl apply -f deploy/operator-serviceaccount.yaml -n podmortem-system
kubectl apply -f deploy/operator-rbac.yaml
for crd in podmortems aiproviders patternlibraries; do
  kubectl wait --for condition=established "crd/${crd}.podmortem.tpu.dev" --timeout=60s
done

E2E_CLUSTER=1 python -m pytest tests/test_e2e_cluster.py -x -q -s
