#!/usr/bin/env python
"""Sustained open-loop soak of the WHOLE pipeline: fake apiserver feeding
the real serving engine.

The north star (BASELINE.md) is ">=100 explanations/min sustained with
p50 < 2 s" — *sustained* is the half a 60 s bench window can't show.
This harness runs the operator control plane (watcher -> pattern engine
-> tpu-native provider -> storage -> events) against the in-memory fake
apiserver for SOAK_SECONDS, injecting pod failures as a Poisson process
at SOAK_RATE/min, and reports:

- arrival -> durable-annotation latency p50/p99 (the user-visible SLO,
  measured at the etcd-equivalent write, not at engine completion)
- completions, in-window throughput, stragglers at the deadline
- leak audit after drain: KV pages back on the free list, zero active or
  reserved slots, engine reset (auto-recovery) count

Knobs (env): SOAK_SECONDS (600), SOAK_RATE (100, arrivals/min),
SOAK_MODEL (tinyllama-1.1b; tiny-test under JAX_PLATFORMS=cpu),
SOAK_SLOTS (16), SOAK_MAX_TOKENS (96), SOAK_DRAIN_S (120).

Prints one JSON line; exit 1 when the leak audit fails.

Run on the TPU host via scripts/tpu_experiments.sh (`run soak ...`), or
anywhere with JAX_PLATFORMS=cpu for a smoke soak.
"""

from __future__ import annotations

import asyncio
import json
import os
import pathlib
import random
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

FIXTURES = REPO / "tests" / "fixtures"


def _percentile(values: list, q: float) -> float:
    if not values:
        return float("nan")
    ordered = sorted(values)
    idx = min(len(ordered) - 1, max(0, round(q * (len(ordered) - 1))))
    return ordered[idx]


async def main() -> int:
    # the container sitecustomize force-registers the TPU plugin; env
    # JAX_PLATFORMS=cpu alone does NOT stop jax.devices() from probing the
    # tunnel (and hanging when it is down/claimed) — pin before any
    # backend query (shared shim, scripts/_cpu_pin.py)
    sys.path.insert(0, str(REPO / "scripts"))
    from _cpu_pin import pin_cpu_if_requested

    pin_cpu_if_requested()
    import jax

    from operator_tpu.utils.platform import enable_persistent_compilation_cache

    enable_persistent_compilation_cache()

    from operator_tpu.utils.compilewatch import CompileWatcher

    compile_watch = CompileWatcher()

    from operator_tpu.operator.app import Operator
    from operator_tpu.operator.kubeapi import FakeKubeApi
    from operator_tpu.operator.storage import ANNOTATION_ANALYZED_AT
    from operator_tpu.schema import (
        AIProvider,
        AIProviderRef,
        AIProviderSpec,
        ContainerState,
        ContainerStateTerminated,
        ContainerStatus,
        LabelSelector,
        ObjectMeta,
        Pod,
        Podmortem,
        PodmortemSpec,
        PodStatus,
    )
    from operator_tpu.utils.config import OperatorConfig

    platform = jax.devices()[0].platform
    default_model = "tiny-test" if platform == "cpu" else "tinyllama-1.1b"
    seconds = float(os.environ.get("SOAK_SECONDS", "600"))
    rate_per_min = float(os.environ.get("SOAK_RATE", "100"))
    model_id = os.environ.get("SOAK_MODEL", default_model)
    slots = int(os.environ.get("SOAK_SLOTS", "16"))
    max_tokens = int(os.environ.get("SOAK_MAX_TOKENS", "96"))
    drain_s = float(os.environ.get("SOAK_DRAIN_S", "120"))

    logs = sorted(FIXTURES.glob("*.log"))
    assert logs, f"no fixture logs under {FIXTURES}"
    corpus = [p.read_text()[-4096:] for p in logs]

    api = FakeKubeApi()
    config = OperatorConfig(
        pattern_cache_directory="/nonexistent",
        health_port=-1,
        completion_api_host="127.0.0.1",
        completion_api_port=0,  # builds + warms the shared engine
        model_id=model_id,
        allow_random_weights=True,
        max_batch_size=slots,
        watch_restart_delay_s=0.01,
        conflict_backoff_base_s=0.001,
    )
    app = Operator(api, config=config)
    await app.start()
    try:
        # wait out weight load + warmup compile BEFORE arrivals start: the
        # soak measures steady state, readiness covers the cold window
        await asyncio.wait_for(app.completion_task, timeout=1800)
        if app.completion_server is None:
            print(json.dumps({"metric": "soak", "error": "engine failed to build"}))
            return 1
        engine = app.completion_server.engine

        provider = AIProvider(
            metadata=ObjectMeta(name="soak-provider", namespace="podmortem-system"),
            spec=AIProviderSpec(provider_id="tpu-native", model_id=model_id,
                                max_tokens=max_tokens),
        )
        await api.create("AIProvider", provider.to_dict())
        pm = Podmortem(
            metadata=ObjectMeta(name="soak", namespace="podmortem-system"),
            spec=PodmortemSpec(
                pod_selector=LabelSelector(match_labels={"app": "soak"}),
                ai_provider_ref=AIProviderRef(name="soak-provider",
                                              namespace="podmortem-system"),
            ),
        )
        await api.create("Podmortem", pm.to_dict())
        await app.watcher.cache.prime()

        # everything compiled from here on is a mid-run compile: an SLO
        # violation (the p99 tail at 100/min), not just noise.  The soak
        # reports each one with its offset into the run and build time.
        compile_watch.mark()

        rng = random.Random(0)
        started = time.monotonic()
        deadline = started + seconds
        submitted: dict[str, float] = {}
        latencies: list[float] = []
        in_window = 0

        polling = True

        async def poll_completions() -> None:
            # runs until the main loop clears `polling` (NOT until
            # `submitted` drains: it starts before the first arrival)
            nonlocal in_window
            while polling:
                done = []
                for name, t0 in submitted.items():
                    try:
                        pod = await api.get("Pod", name, "soak-ns")
                    except Exception:
                        continue
                    annotations = (pod.get("metadata") or {}).get("annotations") or {}
                    if ANNOTATION_ANALYZED_AT in annotations:
                        dt = time.monotonic() - t0
                        latencies.append(dt)
                        if time.monotonic() < deadline:
                            in_window += 1
                        done.append(name)
                for name in done:
                    del submitted[name]
                await asyncio.sleep(0.25)

        poller = asyncio.create_task(poll_completions())

        i = 0
        while time.monotonic() < deadline:
            # Poisson process: exponential inter-arrival gaps
            await asyncio.sleep(rng.expovariate(rate_per_min / 60.0))
            if time.monotonic() >= deadline:
                break
            name = f"soak-{i}"
            i += 1
            pod = Pod(
                metadata=ObjectMeta(name=name, namespace="soak-ns",
                                    labels={"app": "soak"}),
                status=PodStatus(phase="Running", container_statuses=[
                    ContainerStatus(
                        name="app", restart_count=1,
                        state=ContainerState(terminated=ContainerStateTerminated(
                            exit_code=137,
                            finished_at=f"2026-07-30T00:00:{i % 60:02d}Z")),
                    )]),
            )
            await api.create("Pod", pod.to_dict())
            api.set_pod_log("soak-ns", name, corpus[i % len(corpus)])
            submitted[name] = time.monotonic()
            await app.watcher.handle_pod_event("MODIFIED", pod)

        arrivals = i
        # drain: stragglers get a bounded window, then count as incomplete
        try:
            await asyncio.wait_for(app.watcher.drain(), timeout=drain_s)
        except asyncio.TimeoutError:
            pass
        drain_deadline = time.monotonic() + 10
        while submitted and time.monotonic() < drain_deadline:
            await asyncio.sleep(0.5)
        stragglers = len(submitted)
        polling = False
        await poller

        # ---- leak audit ------------------------------------------------
        generator = engine.generator
        leaks = {}
        if generator.paged:
            allocator = generator.allocator
            free = len(allocator._free)
            # minus the trash page and the generator-owned shared-prefix
            # pages (held for the engine's lifetime by design)
            held = int(getattr(generator, "prefix_held_pages", 0))
            total = allocator.num_pages - 1 - held
            if free != total:
                leaks["kv_pages"] = {"free": free, "total": total,
                                     "prefix_held": held}
        if generator.num_active:
            leaks["active_slots"] = generator.num_active
        if generator._reserved:
            leaks["reserved_slots"] = sorted(generator._reserved)
        resets = len(engine._reset_times)

        wall = time.monotonic() - started
        midrun = compile_watch.events_since_mark()
        record = {
            "metric": "soak",
            "platform": platform,
            "model": model_id,
            "seconds": round(wall, 1),
            "rate_per_min": rate_per_min,
            "arrivals": arrivals,
            "completed": len(latencies),
            "completed_in_window": in_window,
            "stragglers_at_deadline": stragglers,
            "throughput_per_min": round(60.0 * len(latencies) / wall, 1),
            "p50_s": round(_percentile(latencies, 0.50), 3),
            "p90_s": round(_percentile(latencies, 0.90), 3),
            "p99_s": round(_percentile(latencies, 0.99), 3),
            "engine_resets": resets,
            "midrun_compiles": len(midrun),
            "midrun_compile_events": [
                {"t_s": round(t, 1), "name": n,
                 "build_s": round(d, 2) if d is not None else None}
                for t, n, d in midrun[:40]
            ],
            "leaks": leaks or None,
            "slo_p50_under_2s": (
                bool(latencies) and _percentile(latencies, 0.50) < 2.0
            ),
        }
        print(json.dumps(record), flush=True)
        return 1 if leaks else 0
    finally:
        await app.stop()


if __name__ == "__main__":
    raise SystemExit(asyncio.run(main()))
