#!/bin/bash
# Round-5 TPU experiment series (run on the TPU-attached host).
# Produces $OUT/: hardware floors, decode attribution, bench variants
# (pipeline, page size, quant, config-4 slots=32, 8B int8, chunked A/B),
# and an xplane profile. Each step is individually timeboxed so one hang
# doesn't kill the series, and EVERY completed step commits the refreshed
# docs/R5_RESULTS.md — a mid-series tunnel death leaves partial evidence
# in git (round 3 lost everything to an all-or-nothing queue).
set -u
OUT=$(realpath -m "${1:-$(cd "$(dirname "$0")/.." && pwd)/r5_experiments}")  # absolute BEFORE the cd below
mkdir -p "$OUT"
cd "$(dirname "$0")/.."
# the keep-host-quiet flag must not outlive the series: the EXIT trap
# covers normal exits + SIGTERM/ctrl-C, and the flag carries this PID so
# consumers can detect a SIGKILL'd (e.g. OOM-killed) series — treat the
# flag as stale when `kill -0 $(cat RUNNING)` fails
trap 'rm -f "$OUT/RUNNING"' EXIT
# one persistent XLA-executable cache across every step: each bench step is
# a fresh process that would otherwise re-pay the whole program grid's
# Mosaic/XLA compiles; the driver's own bench run shares it too
export OPERATOR_TPU_XLA_CACHE_DIR="$OUT/xla_cache"

wait_chip() {  # block until the TPU answers a device probe (a step killed at
  # its timebox can leave the tunnel holding the chip for a while; starting
  # the next step immediately makes its backend probe hang -> cpu fallback)
  for _ in $(seq 1 30); do
    if timeout 60 python -c "import jax; assert jax.devices()[0].platform == 'tpu'" \
        > /dev/null 2>&1; then
      return 0
    fi
    echo "  (chip busy; waiting)" | tee -a "$OUT/series.log"
    sleep 10
  done
  echo "  chip never came back" | tee -a "$OUT/series.log"
  return 1
}

capture() {  # refresh the results doc and commit it (index-lock tolerant)
  python scripts/summarize_series.py "$OUT" docs/R5_RESULTS.md \
      >> "$OUT/series.log" 2>&1
  if [ -f docs/R5_RESULTS.md ] && { \
      ! git ls-files --error-unmatch docs/R5_RESULTS.md > /dev/null 2>&1 \
      || ! git diff --quiet HEAD -- docs/R5_RESULTS.md 2>/dev/null; }; then
    for _ in 1 2 3; do
      git add docs/R5_RESULTS.md 2>/dev/null \
        && git commit -m "Record on-chip result: $1" \
            -- docs/R5_RESULTS.md >> "$OUT/series.log" 2>&1 \
        && break
      sleep 5  # another process may hold .git/index.lock
    done
  fi
}

run() {  # run <name> <timeout_s> <cmd...>
  local name=$1 tmo=$2; shift 2
  # resumable: a relaunch after a mid-series tunnel death (watcher rc=2
  # loop) skips steps that already completed cleanly
  if grep -q "^rc=0 $name\$" "$OUT/series.log" 2>/dev/null; then
    echo "skip $name (already done)" | tee -a "$OUT/series.log"
    return 0
  fi
  echo "=== $name ($(date +%H:%M:%S)) ===" | tee -a "$OUT/series.log"
  # a dead tunnel fails every step: abort the series rather than serially
  # burning each step's full wait window (an outer watcher relaunches)
  wait_chip || { echo "ABORT series at $name (no chip)" | tee -a "$OUT/series.log"; rm -f "$OUT/RUNNING"; exit 2; }
  echo $$ > "$OUT/RUNNING"  # keep the host quiet (tunnel dispatch is host-bound)
  timeout --kill-after=30 "$tmo" "$@" > "$OUT/$name.log" 2>&1
  local rc=$?
  rm -f "$OUT/RUNNING"
  # capture BEFORE writing the resume marker: a kill between the two just
  # reruns the step next time, whereas marker-then-capture would resume
  # PAST a step whose evidence never got committed
  capture "$name"
  echo "rc=$rc $name" | tee -a "$OUT/series.log"
}

# ORDER = verdict priority under an uncertain tunnel (r5: two rounds of
# outage so far): each tier is self-contained evidence, so a short window
# still yields the north-star numbers even if the series dies mid-flight.
#
# tier 1 — de-risk: kernels (VERDICT r4 item 3: Mosaic lowering + parity
# of all four Pallas kernels) before anything long runs
run kernels_smoke 900 python scripts/tpu_kernel_smoke.py
# tier 2 — the north-star evidence itself:
# headline: TinyLlama bf16, paged, pipeline 2, open-loop SLO sweep
run bench_main   2400 env BENCH_OPEN_SECONDS=60 BENCH_SWEEP=60,100,150 python bench.py
# north-star model class: llama-3-8b int8 (~8.2 GB) on the 16 GB chip
run bench_8b     2400 env BENCH_OPEN=0 BENCH_MODEL=llama-3-8b BENCH_QUANT=1 \
    BENCH_SLOTS=8 BENCH_REQUESTS=16 BENCH_MAX_SEQ=2048 python bench.py
# literal BASELINE config 4: 32 slots, 32 concurrent arrivals -> one prefill
run bench_slots32 900 env BENCH_OPEN=0 BENCH_SLOTS=32 python bench.py
# the "sustained" half of the north star: >=10 min open loop at 100/min
# THROUGH the operator pipeline (fake apiserver -> watcher -> pattern
# engine -> tpu-native provider -> storage), with a leak audit at drain
run bench_soak  1800 env SOAK_SECONDS=600 SOAK_RATE=100 python scripts/soak.py
# tier 3 — floors + attribution:
# the single probe that settles the roofline question (VERDICT r3 weak #5):
# the fixed weights-streaming leg of the floor profiler
run floor        600 python scripts/profile_floor.py
run decode_attr  900 python scripts/profile_decode.py
# decode-ahead off (attribution of the pipelining win)
run bench_nopipe 900 env BENCH_OPEN=0 BENCH_PIPELINE=1 python bench.py
# bigger pages: 4x fewer grid steps in the paged kernel
run bench_page256 900 env BENCH_OPEN=0 BENCH_PAGE_SIZE=256 python bench.py
# contiguous cache: is paging costing anything at bench shapes?
run bench_contig 900 env BENCH_OPEN=0 BENCH_PAGED=0 python bench.py
# int8 weights: the bandwidth-halving claim, measured
run bench_quant  900 env BENCH_OPEN=0 BENCH_QUANT=1 python bench.py
# v2 paged kernel: in-kernel DMA of live pages only (vs v1 full-grid DMA)
run bench_kernel_v2 900 env BENCH_OPEN=0 OPERATOR_TPU_PAGED_KERNEL=v2 python bench.py
# flash prefill kernel (Pallas) instead of dense/chunked XLA prefill
run bench_flash  900 env BENCH_OPEN=0 OPERATOR_TPU_FLASH_PREFILL=1 python bench.py
# shared-prefix caching off: attribution of the template-prefill win
run bench_noprefix 900 env BENCH_OPEN=0 BENCH_PREFIX_CACHE=0 python bench.py
# layer-scan unrolling: does scan ys-stacking cost decode bandwidth?
run bench_unroll 900 env BENCH_OPEN=0 OPERATOR_TPU_LAYER_UNROLL=22 python bench.py
# decode-block straight-lining: does the scan CARRY (cache) get copied?
run bench_block_unroll 900 env BENCH_OPEN=0 OPERATOR_TPU_DECODE_UNROLL=1 python bench.py
# chunked prefill A/B in the regime it was built for (VERDICT r3 item 4):
# open-loop p50/p99 vs bench_main at 1B, and an 8B closed-batch pair.
# PREFIX_CACHE off: prefix-shared waves skip the chunk job entirely, so
# these rows must disable it to measure CHUNKING, not the prefix cache
run bench_chunked 1500 env BENCH_OPEN_SECONDS=60 BENCH_PREFILL_CHUNK=256 \
    BENCH_PREFIX_CACHE=0 python bench.py
run bench_8b_chunked 2400 env BENCH_OPEN=0 BENCH_MODEL=llama-3-8b BENCH_QUANT=1 \
    BENCH_SLOTS=8 BENCH_REQUESTS=16 BENCH_MAX_SEQ=2048 BENCH_PREFILL_CHUNK=512 \
    BENCH_PREFIX_CACHE=0 python bench.py
# xplane trace of the timed region for the remaining-gap attribution
run bench_profile 900 env BENCH_OPEN=0 BENCH_PROFILE=$OUT/xplane python bench.py
run trace_summary 300 python scripts/analyze_xplane.py "$OUT/xplane" 40
# the 8B v5e AOT memory record (compiler-confirmed HBM budget): needs the
# axon compile service, which is only reliably up when the tunnel is
run aot_8b      1200 python scripts/aot_8b_check.py
echo "series done $(date +%H:%M:%S)" | tee -a "$OUT/series.log"
