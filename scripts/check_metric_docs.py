#!/usr/bin/env python
"""CI lint: every ``podmortem_*`` metric the code can emit must be
documented under docs/.

Two emission shapes are scanned in ``operator_tpu/``:

- ``metrics.incr("name")`` — rendered by the registry as
  ``podmortem_<name>_total`` (utils/timing.py prometheus());
- literal ``"podmortem_..."`` strings (the stage-summary metric name).

Exit 1 listing any metric that no markdown file under docs/ mentions —
an operator alerting on an undocumented counter name is debugging blind.
"""

from __future__ import annotations

import pathlib
import re
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
#: every string literal inside an .incr(...) argument list (conditional
#: expressions like incr("a" if x else "b") emit BOTH names)
INCR_CALL = re.compile(r"\.incr\(([^)]*)\)", re.DOTALL)
STRING = re.compile(r"[\"']([a-z0-9_]+)[\"']")
#: fully-formed metric names in code (the stage-summary constant); a bare
#: "podmortem_..." dict key without a metric suffix is not a metric
LITERAL = re.compile(
    r"[\"'](podmortem_[a-z0-9_]+_total|podmortem_[a-z0-9_]+_milliseconds)[\"']"
)


def emitted_metrics() -> set[str]:
    metrics: set[str] = set()
    for path in (ROOT / "operator_tpu").rglob("*.py"):
        text = path.read_text(encoding="utf-8", errors="replace")
        for args in INCR_CALL.findall(text):
            for name in STRING.findall(args):
                metrics.add(f"podmortem_{name}_total")
        for name in LITERAL.findall(text):
            metrics.add(name)
    return metrics


def documented_text() -> str:
    blobs = []
    for path in (ROOT / "docs").glob("*.md"):
        blobs.append(path.read_text(encoding="utf-8", errors="replace"))
    blobs.append((ROOT / "README.md").read_text(encoding="utf-8", errors="replace"))
    return "\n".join(blobs)


def main() -> int:
    docs = documented_text()
    missing = sorted(m for m in emitted_metrics() if m not in docs)
    if missing:
        print("undocumented podmortem_* metrics (add them to docs/METRICS.md):")
        for name in missing:
            print(f"  - {name}")
        return 1
    print(f"all {len(emitted_metrics())} emitted podmortem_* metrics are documented")
    return 0


if __name__ == "__main__":
    sys.exit(main())
