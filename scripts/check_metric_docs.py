#!/usr/bin/env python
"""CI lint: every ``podmortem_*`` metric the code can emit must be
documented under docs/.

Thin shim: the scan now lives in graftlint's GL005 rule
(``operator_tpu/analysis/rules/gl005_drift.py``) so the metric-docs
contract is enforced by ``python -m operator_tpu.analysis`` alongside the
other generated-artifact checks.  This entry point is kept so existing CI
invocations (and operator runbooks) of ``python scripts/check_metric_docs.py``
keep working with the same verdict and output.
"""

from __future__ import annotations

import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

from operator_tpu.analysis.rules.gl005_drift import (  # noqa: E402
    emitted_metrics as _emitted_metrics,
    undocumented_metrics,
)


def emitted_metrics() -> set[str]:
    return _emitted_metrics(ROOT)


def main() -> int:
    missing = undocumented_metrics(ROOT)
    if missing:
        print("undocumented podmortem_* metrics (add them to docs/METRICS.md):")
        for name in missing:
            print(f"  - {name}")
        return 1
    print(f"all {len(emitted_metrics())} emitted podmortem_* metrics are documented")
    return 0


if __name__ == "__main__":
    sys.exit(main())
