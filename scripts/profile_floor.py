#!/usr/bin/env python
"""Hardware floors for the decode roofline on this chip.

1. HBM bandwidth: elementwise update over a 1 GB array.
2. MXU: 8192^3 bf16 matmul.
3. Weights-streaming floor: lax.scan over 22 stacked TinyLlama layers,
   batch-16 activations through the 7 layer matmuls + lm_head — the decode
   step minus attention/cache/sampling. Run as a scan-of-K outer block like
   the engine's decode block.

FLOOR_SMOKE=1 shrinks every leg to trivial CPU shapes (MiB transfer,
256^3 matmul, 2 layers) and pins the cpu backend: it proves the probes
compile+run without the chip — round 3 lost its floor measurement to a
leg first executed ON the chip that didn't compile.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(__file__))
from _cpu_pin import pin_cpu_if_requested

SMOKE = os.environ.get("FLOOR_SMOKE", "0") == "1"
pin_cpu_if_requested(force=SMOKE)  # smoke must never touch the tunnel

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")


def fetch_time(probe_fn, iters, warmup=2):
    for _ in range(warmup):
        p = probe_fn()
    np.asarray(p)
    t0 = time.perf_counter()
    for _ in range(iters):
        p = probe_fn()
    np.asarray(p)
    return (time.perf_counter() - t0) / iters


def main():
    print(f"device: {jax.devices()[0]}", flush=True)
    key = jax.random.PRNGKey(0)

    # 1. HBM bandwidth ------------------------------------------------------
    nbytes = 1 << (20 if SMOKE else 30)
    x = jnp.zeros((nbytes // 2,), jnp.bfloat16)

    @jax.jit
    def bump(x):
        return x * 1.0001 + 1.0

    state = {"x": x}
    def step():
        state["x"] = bump(state["x"])
        return state["x"][:1]
    dt = fetch_time(step, iters=10)
    # read + write = 2x nbytes per iteration (2 GB full, 2 MiB smoke)
    print(f"HBM elementwise: {dt*1e3:.2f} ms for {nbytes/2**30:.3g} GiB r+w -> "
          f"{2*nbytes/dt/1e9:.0f} GB/s", flush=True)

    # 2. MXU ---------------------------------------------------------------
    n = 256 if SMOKE else 8192
    a = jax.random.normal(key, (n, n), jnp.bfloat16)

    @jax.jit
    def mat(a):
        return a @ a

    state = {"a": a}
    def step2():
        state["a"] = mat(state["a"])
        return state["a"][:1, :1]
    dt = fetch_time(step2, iters=10)
    print(f"MXU {n}^3 bf16: {dt*1e3:.2f} ms -> {2*n**3/dt/1e12:.0f} TFLOP/s", flush=True)

    # 3. weights-streaming floor -------------------------------------------
    if SMOKE:
        B, H, F, L = 4, 128, 256, 2
        QH, KH, D, V = 4, 2, 32, 1024
    else:
        B, H, F, L = 16, 2048, 5632, 22
        QH, KH, D, V = 32, 4, 64, 32000
    keys = jax.random.split(key, 8)
    layers = {
        "wq": jax.random.normal(keys[0], (L, H, QH * D), jnp.bfloat16),
        "wk": jax.random.normal(keys[1], (L, H, KH * D), jnp.bfloat16),
        "wv": jax.random.normal(keys[2], (L, H, KH * D), jnp.bfloat16),
        "wo": jax.random.normal(keys[3], (L, QH * D, H), jnp.bfloat16),
        "w_gate": jax.random.normal(keys[4], (L, H, F), jnp.bfloat16),
        "w_up": jax.random.normal(keys[5], (L, H, F), jnp.bfloat16),
        "w_down": jax.random.normal(keys[6], (L, F, H), jnp.bfloat16),
    }
    head = jax.random.normal(keys[7], (H, V), jnp.bfloat16)
    wbytes = sum(w.nbytes for w in jax.tree_util.tree_leaves(layers)) + head.nbytes
    print(f"streamed weights: {wbytes/1e9:.2f} GB", flush=True)

    def layer_step(x, w):
        q = x @ w["wq"]
        k = x @ w["wk"]
        v = x @ w["wv"]
        x = x + (q * 0.01) @ w["wo"] + (k @ w["wk"].T + v @ w["wv"].T) * 1e-6
        gate = jax.nn.silu(x @ w["w_gate"])
        up = x @ w["w_up"]
        x = x + (gate * up) @ w["w_down"]
        return x * 0.999, None

    def one_token(x, layers, head):
        x, _ = jax.lax.scan(layer_step, x, layers)
        logits = (x @ head).astype(jnp.float32)
        return x * 0.9 + logits[:, :H].astype(jnp.bfloat16) * 1e-6

    for K in (1, 8):
        # weights are runtime ARGUMENTS, not closed-over constants: capturing
        # 2 GB as constants makes lowering/compile pathologically slow on a
        # tunneled backend and lets XLA constant-fold the thing being measured
        @jax.jit
        def block(x, layers, head, K=K):
            def body(x, _):
                return one_token(x, layers, head), None
            x, _ = jax.lax.scan(body, x, None, length=K)
            return x

        x0 = jax.random.normal(key, (B, H), jnp.bfloat16)
        state3 = {"x": x0}
        def step3():
            state3["x"] = block(state3["x"], layers, head)
            return state3["x"][:1, :1]
        dt = fetch_time(step3, iters=8)
        per = dt / K
        print(f"stream floor (block {K}): {per*1e3:.2f} ms/token-step -> "
              f"{wbytes/per/1e9:.0f} GB/s effective", flush=True)


if __name__ == "__main__":
    main()
