// Aho-Corasick multi-pattern scanner — the native hot loop of the pattern
// engine's literal prefilter (operator_tpu/patterns/prefilter.py).
//
// Role: one pass over the raw log finds every occurrence of every
// pattern-library literal, replacing O(patterns x lines) Python regex
// scans with O(text) native scanning; only the surviving (pattern, line)
// candidates are re-checked by the full regex.  This is the rebuild's
// native data-path component (the reference's only native artifact is an
// AOT build of its whole operator, SURVEY.md SS2) - scanning is the one
// CPU-bound stage between kube watch and the TPU programs.
//
// Plain C ABI for ctypes: build once per pattern-library reload, scan per
// failure log.  No global state; handles are heap objects.

#include <cstdint>
#include <cstring>
#include <queue>
#include <vector>

namespace {

struct Automaton {
    // dense transition table: node * 256 -> node (flat for cache locality)
    std::vector<int32_t> next;
    std::vector<int32_t> fail;
    std::vector<std::vector<int32_t>> out;  // pattern ids ending at node
    int32_t nodes = 0;

    int32_t alloc_node() {
        next.resize(next.size() + 256, -1);
        fail.push_back(0);
        out.emplace_back();
        return nodes++;
    }

    int32_t& trans(int32_t node, uint8_t byte) { return next[node * 256 + byte]; }
};

}  // namespace

extern "C" {

// Build an automaton over n literals (arbitrary bytes, lens[i] each).
// Returns an opaque handle (never null; zero patterns is a valid build).
void* ls_build(const char** patterns, const int32_t* lens, int32_t n) {
    auto* a = new Automaton();
    a->alloc_node();  // root
    for (int32_t pattern_id = 0; pattern_id < n; ++pattern_id) {
        int32_t node = 0;
        for (int32_t i = 0; i < lens[pattern_id]; ++i) {
            uint8_t byte = static_cast<uint8_t>(patterns[pattern_id][i]);
            int32_t next_node = a->trans(node, byte);
            if (next_node < 0) {
                next_node = a->alloc_node();
                a->trans(node, byte) = next_node;
            }
            node = next_node;
        }
        if (lens[pattern_id] > 0) a->out[node].push_back(pattern_id);
    }
    // BFS failure links; missing root transitions loop to root
    std::queue<int32_t> queue;
    for (int32_t byte = 0; byte < 256; ++byte) {
        int32_t child = a->trans(0, static_cast<uint8_t>(byte));
        if (child < 0) {
            a->trans(0, static_cast<uint8_t>(byte)) = 0;
        } else {
            a->fail[child] = 0;
            queue.push(child);
        }
    }
    while (!queue.empty()) {
        int32_t node = queue.front();
        queue.pop();
        for (int32_t byte = 0; byte < 256; ++byte) {
            int32_t child = a->trans(node, static_cast<uint8_t>(byte));
            int32_t via_fail = a->trans(a->fail[node], static_cast<uint8_t>(byte));
            if (child < 0) {
                a->trans(node, static_cast<uint8_t>(byte)) = via_fail;
            } else {
                a->fail[child] = via_fail;
                // merge output set of the failure target (suffix matches)
                const auto& suffix_out = a->out[via_fail];
                a->out[child].insert(a->out[child].end(), suffix_out.begin(),
                                     suffix_out.end());
                queue.push(child);
            }
        }
    }
    return a;
}

// Scan text; for each literal occurrence write (pattern_id, end_offset)
// into the out arrays.  Returns the number of hits written (capped at
// max_hits; further matches are dropped — callers size generously).
int64_t ls_scan(void* handle, const char* text, int64_t len, int32_t* out_ids,
                int64_t* out_offsets, int64_t max_hits) {
    auto* a = static_cast<Automaton*>(handle);
    int64_t hits = 0;
    int32_t node = 0;
    for (int64_t i = 0; i < len; ++i) {
        node = a->next[node * 256 + static_cast<uint8_t>(text[i])];
        const auto& out = a->out[node];
        for (int32_t pattern_id : out) {
            if (hits >= max_hits) return hits;
            out_ids[hits] = pattern_id;
            out_offsets[hits] = i;  // offset of the literal's LAST byte
            ++hits;
        }
    }
    return hits;
}

void ls_free(void* handle) { delete static_cast<Automaton*>(handle); }

}  // extern "C"
