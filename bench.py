#!/usr/bin/env python
"""End-to-end benchmark: pod-failure explanations per minute on one chip.

Replays recorded failure logs through the REAL pipeline — pattern match
(CPU) -> prompt build -> continuous-batching LLM generation on the TPU
(operator_tpu.serving.engine) — and measures sustained throughput and p50
arrival->completion latency for BENCH_REQUESTS concurrent failure events.

The reference system publishes no benchmarks (BASELINE.md); the driver's
north star is >=100 explanations/min sustained with p50 < 2 s.  The primary
JSON metric is explanations/min, vs_baseline = value / 100.

Weights are random-init bf16 (no network egress to fetch checkpoints);
generation speed is weight-value independent, so throughput/latency numbers
are honest.  EOS stopping is disabled so every request generates exactly
BENCH_MAX_TOKENS tokens — deterministic work per request.

Two phases:

1. **closed batch** — BENCH_REQUESTS submitted at t=0 and drained: peak
   batched throughput (the headline expl/min metric).
2. **open loop** — a seeded failure storm at BENCH_RATE/min for
   BENCH_OPEN_SECONDS through the FULL operator->router->serving stack
   (operator_tpu/loadgen/), with SLO accounting from the ledger
   (obs/sloledger.py): offered vs achieved, per-class attainment,
   goodput-under-SLO, shed/deadline-exceeded breakdown, and the
   two-replay determinism gate (``replay_identical``).  The closed
   batch's p50 ~= wall time is a queueing artifact (VERDICT r2 weak #2);
   this phase is the honest number.  Set BENCH_OPEN=0 to skip,
   BENCH_SWEEP="60,100,150" for a rate sweep.  On cpu-fallback the storm
   runs compressed (BENCH_OPEN_TIME_SCALE) over synthetic replicas —
   same operator stack, engine-less serving.

Knobs (env): BENCH_MODEL (tinyllama-1.1b), BENCH_REQUESTS (32),
BENCH_SLOTS (16), BENCH_MAX_TOKENS (96), BENCH_MAX_SEQ (1024),
BENCH_RATE (100), BENCH_OPEN_SECONDS (60), BENCH_TOKENIZER (builtin-bpe).
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def build_requests(n: int) -> list:
    """n AnalysisRequests from the recorded failure fixtures."""
    from operator_tpu.patterns.engine import PatternEngine
    from operator_tpu.schema.analysis import AnalysisRequest, PodFailureData

    fixture_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), "tests", "fixtures")
    fixtures = []
    for name in sorted(os.listdir(fixture_dir)):
        if name.endswith(".log"):
            with open(os.path.join(fixture_dir, name)) as f:
                fixtures.append(f.read())
    assert fixtures, "no .log fixtures found"

    engine = PatternEngine()
    requests = []
    for i in range(n):
        failure = PodFailureData(logs=fixtures[i % len(fixtures)])
        result = engine.analyze(failure)
        requests.append(AnalysisRequest(analysis_result=result, failure_data=failure))
    return requests


async def run_open_loop(
    replicas,
    *,
    rate_per_min: float,
    duration_s: float,
    seed: int = 0,
    time_scale: float = 1.0,
    drain_s: float = 60.0,
) -> dict:
    """One seeded open-loop failure storm through the FULL stack —
    operator pipeline -> router -> serving replicas (operator_tpu/loadgen/)
    — with SLO accounting from the ledger (obs/sloledger.py).

    Arrivals are a seeded storm schedule materialised up front and fired
    whether or not the system keeps up (arrivals never wait in line);
    the record reports offered vs achieved, per-class latency
    percentiles, attainment, goodput-under-SLO, and the shed /
    deadline-exceeded breakdown.  The schedule is materialised TWICE
    independently and the record carries ``replay_identical`` — the
    two-replay determinism gate — plus a zero-torn-lines audit of the
    ledger journal."""
    import tempfile

    from operator_tpu.loadgen import ArrivalProcess, ArrivalSpec
    from operator_tpu.loadgen.storm import build_storm_stack, run_storm

    spec = ArrivalSpec(
        name="storm", rate_per_min=rate_per_min, duration_s=duration_s,
    )
    process = ArrivalProcess(spec, seed=seed)
    replay = ArrivalProcess(spec, seed=seed)
    replay_identical = (
        process.fingerprint() == replay.fingerprint()
        and [e.to_dict() for e in process.materialize()]
        == [e.to_dict() for e in replay.materialize()]
    )
    with tempfile.TemporaryDirectory(prefix="bench-slo-") as tmp:
        ledger_path = os.path.join(tmp, "slo-ledger.jsonl")
        stack = await build_storm_stack(
            replicas=replicas, time_scale=time_scale,
            ledger_path=ledger_path,
        )
        report = await run_storm(stack, process, drain_s=drain_s)
        stack.close()
        torn = 0
        journaled = 0
        with open(ledger_path) as fh:
            for line in fh:
                if not line.strip():
                    continue
                journaled += 1
                try:
                    json.loads(line)
                except ValueError:
                    torn += 1
    total = report["slo"]["total"]
    classes = {
        cls: {
            "target_s": row.get("target_s"),
            "admitted": row["admitted"],
            "attainment": row["attainment"],
            "p50_s": row["p50_s"],
            "p95_s": row["p95_s"],
            "p99_s": row["p99_s"],
            "goodput_analyses_per_min": row["goodput_analyses_per_min"],
            "goodput_tokens_s": row["goodput_tokens_s"],
        }
        for cls, row in report["slo"]["classes"].items()
    }
    # the headline p50: the 2s-target interactive class when present
    # (that is the class the >=100/min SLO gate judges), else the total
    interactive = report["slo"]["classes"].get("interactive") or {}
    return {
        "rate_per_min": rate_per_min,
        "offered": report["arrivals"],
        "offered_per_min": report["offered_per_min"],
        "achieved_per_min": report["achieved_per_min"],
        "completed": total["completed"],
        "attainment": total["attainment"],
        "degraded": total.get("degraded", 0),
        "shed": total["shed"],
        "deadline_exceeded": total["deadline_exceeded"],
        "failed": total["failed"],
        "overload": report.get("overload"),
        "goodput_tokens_s": total["goodput_tokens_s"],
        "goodput_analyses_per_min": total["goodput_analyses_per_min"],
        "p50_s": (interactive.get("p50_s")
                  if interactive.get("p50_s") is not None else total["p50_s"]),
        "p99_s": (interactive.get("p99_s")
                  if interactive.get("p99_s") is not None else total["p99_s"]),
        "classes": classes,
        "fleet": report["fleet"]["fleet"],
        "seed": seed,
        "fingerprint": report["fingerprint"],
        "replay_identical": replay_identical,
        "ledger_lines": journaled,
        "ledger_torn_lines": torn,
    }


async def run_mixed_scenario(engine, long_prompts, short_prompts,
                             long_sampling, short_sampling) -> dict:
    """Mixed long-prefill + short-decode traffic: short requests are
    decoding when the long prompts arrive, so a phase-separated engine
    stalls them behind the batched prefill while the continuous
    scheduler (serving/sched/) keeps their tokens flowing.  Returns
    latency stats; occupancy/stall numbers are read from the engine's
    own metrics by the caller."""
    await engine.start()
    latencies: list[float] = []

    async def one(prompt: str, sampling) -> None:
        started = time.perf_counter()
        await engine.generate(prompt, sampling)
        latencies.append(time.perf_counter() - started)

    tasks = []
    # shorts first: they must be mid-decode when the long prefills land
    for prompt in short_prompts[: len(short_prompts) // 2]:
        tasks.append(asyncio.ensure_future(one(prompt, short_sampling)))
    await asyncio.sleep(0.05)
    for prompt in long_prompts:
        tasks.append(asyncio.ensure_future(one(prompt, long_sampling)))
    for prompt in short_prompts[len(short_prompts) // 2:]:
        await asyncio.sleep(0.01)
        tasks.append(asyncio.ensure_future(one(prompt, short_sampling)))
    wall_start = time.perf_counter()
    await asyncio.gather(*tasks)
    wall = time.perf_counter() - wall_start
    await engine.close()
    latencies.sort()
    n = len(latencies)
    return {
        "completed": n,
        "wall_s": round(wall, 3),
        "p50_s": round(latencies[n // 2], 3) if n else None,
        "p99_s": round(latencies[min(n - 1, int(n * 0.99))], 3) if n else None,
    }


def bench_mixed(params, config, tokenizer, *, slots: int, max_seq: int,
                page_size: int, decode_block: int) -> dict:
    """Run the mixed-traffic scenario under BOTH serving modes on fresh
    engines (fresh metrics registries, shared weights) and report batch
    occupancy + decode-stall alongside latency — the CPU-measurable face
    of the continuous scheduler's win (no TPU in the loop needed).

    The continuous side runs a ``sched_pipeline_depth`` sweep (the
    decode-ahead host-gap story: the host_gap fraction collapses at
    depth >= 2) plus one speculation run on TEMPLATED greedy prompts
    (the repetitive-text case prompt-lookup drafting exists for); its
    ``spec_decode`` block carries acceptance rate, mean accepted
    tokens/round and the measured host-side draft overhead, and
    ``decode_tokens_per_host_sync`` is the headline — 1.0 is the old
    synchronous one-token loop's ceiling."""
    from operator_tpu.serving.engine import (
        BatchedGenerator, SamplingParams, ServingEngine,
    )
    from operator_tpu.serving.sched import Scheduler
    from operator_tpu.utils.timing import MetricsRegistry

    filler = "the pod was OOMKilled after its memory limit was exceeded "
    long_prompts = [filler * (max_seq // (len(filler) // 4)) for _ in range(2)]
    short_prompts = [f"pod crash {i}: exit code 137" for i in range(6)]
    long_sampling = SamplingParams(max_tokens=8, temperature=0.3,
                                   stop_on_eos=False)
    short_sampling = SamplingParams(max_tokens=24, temperature=0.3,
                                    stop_on_eos=False)

    def run_engine(*, mode, depth=1, spec=False, greedy=False):
        metrics = MetricsRegistry()
        generator = BatchedGenerator(
            params, config, tokenizer, max_slots=slots, max_seq=max_seq,
            paged=True, page_size=page_size, metrics=metrics,
            decode_block=decode_block if mode == "wave" else 1,
        )
        scheduler = None
        if mode == "continuous":
            scheduler = Scheduler(
                generator, chunk=64, pipeline_depth=depth,
                spec_decode=spec, spec_lookup_k=4,
            )
        engine = ServingEngine(
            generator, admission_wait_s=0.002, scheduler=scheduler
        )
        # speculation only drafts for greedy rows (byte-identical
        # acceptance needs argmax); the sweep keeps the sampled traffic
        long_s, short_s = long_sampling, short_sampling
        if greedy:
            long_s = SamplingParams(max_tokens=8, temperature=0.0,
                                    stop_on_eos=False)
            short_s = SamplingParams(max_tokens=24, temperature=0.0,
                                     stop_on_eos=False)
        result = asyncio.run(run_mixed_scenario(
            engine, long_prompts, short_prompts, long_s, short_s
        ))
        return result, generator, scheduler

    out: dict = {}
    result, generator, _ = run_engine(mode="wave")
    occupancy = generator.metrics.stage("batch_occupancy")
    stall = generator.metrics.stage("decode_stall")
    result["batch_occupancy_avg"] = (
        round(occupancy.mean_ms / 100.0, 4) if occupancy.count else None
    )
    result["decode_stall_steps"] = stall.count
    result["decode_stall_ms_total"] = round(stall.mean_ms * stall.count, 1)
    out["wave"] = result
    log(f"mixed[wave]: occupancy={result['batch_occupancy_avg']} "
        f"stall_steps={result['decode_stall_steps']} "
        f"stall_ms={result['decode_stall_ms_total']} "
        f"p50={result['p50_s']}s wall={result['wall_s']}s")

    # decode-ahead sweep: spec off so the depth axis is isolated; the
    # host_gap fraction (step-clock attribution) is the acceptance
    # number — it collapses once a wave is always queued behind the
    # in-flight one
    out["sched_pipeline_depth_sweep"] = {}
    for depth in (1, 2, 4):
        result, generator, scheduler = run_engine(
            mode="continuous", depth=depth
        )
        stats = scheduler.stats()
        summary = generator.step_clock.summary()
        fractions = summary.get("fractions") or {}
        result["batch_occupancy_avg"] = stats["batch_occupancy_avg"]
        result["decode_stall_steps"] = stats["decode_stall_steps"]
        result["decode_stall_ms_total"] = 0.0
        result["admitted_midwave"] = stats["admitted_midwave"]
        result["chunked_prefills"] = stats["chunked_prefills"]
        result["host_gap_fraction"] = fractions.get("host_gap")
        result["decode_tokens_per_host_sync"] = (
            stats["decode_tokens_per_host_sync"]
        )
        result["dispatch_ahead_steps"] = stats["dispatch_ahead"]
        out["sched_pipeline_depth_sweep"][str(depth)] = result
        if depth == 2:
            out["continuous"] = result  # the shipping default depth
        log(f"mixed[continuous,depth={depth}]: "
            f"occupancy={result['batch_occupancy_avg']} "
            f"host_gap_frac={result['host_gap_fraction']} "
            f"tok/sync={result['decode_tokens_per_host_sync']} "
            f"p50={result['p50_s']}s wall={result['wall_s']}s")

    # prompt-lookup speculation on templated greedy traffic (depth 2 =
    # the serving default, so rest rounds + verify rounds both appear)
    result, generator, scheduler = run_engine(
        mode="continuous", depth=2, spec=True, greedy=True,
    )
    stats = scheduler.stats()
    spec_stats = dict(stats["spec_decode"])
    spec_stats["decode_tokens_per_host_sync"] = (
        stats["decode_tokens_per_host_sync"]
    )
    spec_stats["wall_s"] = result["wall_s"]
    out["spec_decode"] = spec_stats
    log(f"mixed[spec_decode]: acceptance={spec_stats['acceptance_rate']} "
        f"mean_accepted/round={spec_stats['mean_accepted_per_round']} "
        f"draft_overhead_ms={spec_stats['draft_overhead_ms']} "
        f"tok/sync={spec_stats['decode_tokens_per_host_sync']}")
    return out


def bench_kv_economy(params, config, tokenizer, *, slots: int, max_seq: int,
                     page_size: int) -> dict:
    """Measure the KV-economy win (serving/kvstore.py + ops/kv_transfer.py)
    on a fresh continuous engine: TTFT cold (full prefill) vs warm-hit
    (block-hash prefix match) vs restored-from-host (blocks spilled via
    ``Scheduler.spill_cache()``, restored by DMA), the prefill-tokens-saved
    fraction over a templated storm, and resume-vs-restart latency for an
    injected mid-stream kill (token-level streaming resume: the survivor
    re-prefills prompt+generated and decodes only the continuation).

    All lanes run greedy on the same templated prompt set, so the
    byte-identity contract holds and the TTFT deltas are pure KV effects
    (no sampling noise, no recompiles after the first lane warms)."""
    from operator_tpu.ops.kv_transfer import HostKVPool
    from operator_tpu.serving.engine import BatchedGenerator, SamplingParams
    from operator_tpu.serving.kvstore import PrefixKVStore
    from operator_tpu.serving.sched import Scheduler
    from operator_tpu.utils.timing import MetricsRegistry

    metrics = MetricsRegistry()
    generator = BatchedGenerator(
        params, config, tokenizer, max_slots=slots, max_seq=max_seq,
        paged=True, page_size=page_size, metrics=metrics,
    )
    pool_mb = int(os.environ.get("KV_HOST_POOL_MB", "64"))
    store = PrefixKVStore(
        generator.page_size, host_pool=HostKVPool(pool_mb), metrics=metrics,
    )
    sched = Scheduler(generator, kvstore=store)
    template = ("analyse this pod failure: the container was OOMKilled "
                "after exceeding its memory limit; ")
    prompt = template * max(1, (max_seq // 2) // max(1, len(template) // 3))
    one_tok = SamplingParams(max_tokens=1, temperature=0.0, stop_on_eos=False)

    def drain(req_id: int, limit: int = 2000):
        for _ in range(limit):
            for outcome in sched.step():
                if outcome.req_id == req_id:
                    return outcome
        raise RuntimeError("kv bench request never finished")

    def ttft(sampling) -> tuple[float, "object"]:
        started = time.perf_counter()
        outcome = drain(sched.enqueue(prompt, sampling))
        return time.perf_counter() - started, outcome

    # compile the programs OUTSIDE the timed lanes (the cold lane measures
    # prefill work, not XLA) — a throwaway prompt with a distinct head so
    # its blocks never collide with the measured prompt's chain
    drain(sched.enqueue("warmup " + prompt[: len(prompt) // 2], one_tok))

    cold_s, cold = ttft(one_tok)
    warm_s, warm = ttft(one_tok)
    spilled = sched.spill_cache()
    restored_s, restored = ttft(one_tok)
    assert (list(cold.result.token_ids) == list(warm.result.token_ids)
            == list(restored.result.token_ids)), "kv lanes diverged"

    # templated storm: N suffix-varied prompts over the shared template —
    # the saved fraction is the economy headline (prompt tokens the fleet
    # never re-prefills)
    storm_n = int(os.environ.get("BENCH_KV_STORM", "8"))
    saved0 = metrics.counter("kv_prefill_tokens_saved")
    for i in range(storm_n):
        drain(sched.enqueue(prompt + f" incident {i}", one_tok))
    saved = metrics.counter("kv_prefill_tokens_saved") - saved0
    lookups = store.lookups
    storm_prompt_tokens = storm_n * len(tokenizer.encode(prompt))
    saved_frac = round(saved / storm_prompt_tokens, 4) if storm_prompt_tokens else 0.0

    # injected kill: generate the reference stream, then compare resuming
    # from a mid-stream checkpoint against restarting from scratch
    gen_tokens = 16
    reference = drain(sched.enqueue(
        prompt, SamplingParams(max_tokens=gen_tokens, temperature=0.0,
                               stop_on_eos=False),
    ))
    ref_ids = list(reference.result.token_ids)
    kill_at = gen_tokens // 2
    started = time.perf_counter()
    resumed = drain(sched.enqueue(
        prompt,
        SamplingParams(max_tokens=gen_tokens - kill_at, temperature=0.0,
                       stop_on_eos=False),
        resume_tokens=ref_ids[:kill_at],
    ))
    resume_s = time.perf_counter() - started
    started = time.perf_counter()
    restarted = drain(sched.enqueue(
        prompt, SamplingParams(max_tokens=gen_tokens, temperature=0.0,
                               stop_on_eos=False),
    ))
    restart_s = time.perf_counter() - started
    assert ref_ids[:kill_at] + list(resumed.result.token_ids) == ref_ids, \
        "resume lane diverged from the reference stream"
    assert list(restarted.result.token_ids) == ref_ids

    kv = sched.stats()["kv_economy"]
    out = {
        "ttft_cold_s": round(cold_s, 4),
        "ttft_warm_hit_s": round(warm_s, 4),
        "ttft_restored_s": round(restored_s, 4),
        "warm_speedup": round(cold_s / warm_s, 2) if warm_s > 0 else None,
        "restored_speedup": (
            round(cold_s / restored_s, 2) if restored_s > 0 else None
        ),
        "spilled_blocks": spilled,
        "storm_requests": storm_n,
        "prefill_tokens_saved": saved,
        "prefill_saved_frac": saved_frac,
        "prefix_lookups": lookups,
        "hit_rate": kv["hit_rate"],
        "offloads": kv["offloads"],
        "restores": kv["restores"],
        "resume_s": round(resume_s, 4),
        "restart_s": round(restart_s, 4),
        "resume_vs_restart": (
            round(restart_s / resume_s, 2) if resume_s > 0 else None
        ),
    }
    log(f"kv_economy: ttft cold={out['ttft_cold_s']}s "
        f"warm={out['ttft_warm_hit_s']}s (x{out['warm_speedup']}) "
        f"restored={out['ttft_restored_s']}s saved_frac={saved_frac} "
        f"resume={out['resume_s']}s vs restart={out['restart_s']}s")
    return out


def bench_kv_fabric(params, config, tokenizer, *, slots: int, max_seq: int,
                    page_size: int) -> dict:
    """Price the fleet KV fabric (operator_tpu/fabric/, docs/FABRIC.md)
    on CPU smoke:

    - **fetch vs recompute TTFT**: replica A computes a >=8-block prompt
      and mirrors its pages; replica B's cold lane prefills the same
      prompt from scratch, then (cache reset) its warm-peer lane pulls
      A's pages through the real wire format + fetch client and restores
      them by DMA.  The warm-peer time INCLUDES the fetch itself — the
      honest arrival-to-token-one comparison — and both lanes must stay
      greedy byte-identical;
    - **disaggregated vs mixed storm goodput**: the same seeded arrival
      schedule against a 3-mixed fleet and a 1-prefill + 2-decode fleet
      in disaggregated dispatch, goodput-under-SLO each.
    """
    from operator_tpu.fabric import FabricFetcher, FabricIndex, encode_block
    from operator_tpu.loadgen import ArrivalProcess, ArrivalSpec
    from operator_tpu.loadgen.storm import (
        SyntheticReplica, build_storm_stack, run_storm,
    )
    from operator_tpu.ops.kv_transfer import HostKVPool
    from operator_tpu.serving.engine import BatchedGenerator, SamplingParams
    from operator_tpu.serving.kvstore import PrefixKVStore, block_hashes
    from operator_tpu.serving.sched import Scheduler
    from operator_tpu.serving.types import prompt_budget
    from operator_tpu.utils.timing import MetricsRegistry

    # The warm-peer claim is judged on an >=8-block prompt, and the
    # prompt must FIT the truncation budget — or enqueue tail-truncates
    # it and every block hash changes out from under the mirror.  Two
    # traps: the generator clamps max_seq to config.max_seq_len (256
    # for tiny-test), and at the default page_size=64 with max_seq=512
    # the two constraints cannot both hold (8 blocks = 512 tokens > 511
    # budget).  So size the lane's OWN page off the effective budget.
    eff_seq = min(max_seq, config.max_seq_len)
    budget = prompt_budget(eff_seq, 2)
    fabric_page = min(page_size, 32)
    while fabric_page > 8 and 9 * fabric_page > budget:
        fabric_page //= 2

    def make_replica(*, mirror):
        metrics = MetricsRegistry()
        generator = BatchedGenerator(
            params, config, tokenizer, max_slots=slots, max_seq=max_seq,
            paged=True, page_size=fabric_page, metrics=metrics,
        )
        store = PrefixKVStore(
            generator.page_size, host_pool=HostKVPool(64), metrics=metrics,
        )
        return Scheduler(generator, kvstore=store, fabric_mirror=mirror), \
            generator, store

    def drain(sched, req_id, limit=2000):
        for _ in range(limit):
            for outcome in sched.step():
                if outcome.req_id == req_id:
                    return outcome
        raise RuntimeError("kv fabric bench request never finished")

    # two tokens, not one: mirroring piggybacks on the NEXT commit
    # window's host sync (scheduler._drain_mirror), so a 1-token request
    # would finish with its blocks still queued; token two opens exactly
    # one more window.  All three lanes use the same params, so the
    # cold/warm comparison stays equal-footing.
    one_tok = SamplingParams(max_tokens=2, temperature=0.0, stop_on_eos=False)
    template = ("analyse this pod failure: the container was OOMKilled "
                "after exceeding its memory limit; ")
    # grow the prompt in token space, not char space: stop once it spans
    # >8 full blocks, and never cross the truncation budget
    prompt = template
    while (len(tokenizer.encode(prompt)) < 9 * fabric_page
           and len(tokenizer.encode(prompt + template)) <= budget):
        prompt += template
    tokens = tokenizer.encode(prompt)
    hashes = block_hashes(tokens, fabric_page)
    assert len(hashes) >= 8, (
        f"fabric bench prompt spans only {len(hashes)} blocks "
        f"({len(tokens)} tokens at page {fabric_page}, budget {budget}); "
        "the warm-peer claim is judged on >= 8"
    )

    # replica A: the holder — compute + mirror (compile outside the lane)
    sched_a, _gen_a, store_a = make_replica(mirror=True)
    drain(sched_a, sched_a.enqueue("warmup " + prompt[: len(prompt) // 2],
                                   one_tok))
    ref = drain(sched_a, sched_a.enqueue(prompt, one_tok))
    assert all(store_a.host_pool.has(h) for h in hashes), \
        "holder failed to mirror the prompt's blocks"

    index = FabricIndex()
    index.update("bench-a", [h.hex() for h in hashes], url="http://bench-a")

    async def transport(url, budget_s):
        hash_hex = url.rsplit("/", 1)[-1]
        page = store_a.host_pool.get(bytes.fromhex(hash_hex))
        if page is None:
            return 404, b""
        return 200, encode_block(bytes.fromhex(hash_hex), *page)

    # replica B: cold lane (full prefill), then warm-peer lane (fetch +
    # adopt + DMA restore) after a cache reset — same compiled programs
    sched_b, gen_b, store_b = make_replica(mirror=False)
    drain(sched_b, sched_b.enqueue("warmup " + prompt[: len(prompt) // 2],
                                   one_tok))
    started = time.perf_counter()
    cold = drain(sched_b, sched_b.enqueue(prompt, one_tok))
    cold_s = time.perf_counter() - started
    sched_b.reset()

    fetcher = FabricFetcher(
        index, transport=transport, self_id="bench-b",
        metrics=gen_b.metrics,
    )
    started = time.perf_counter()
    adopted = asyncio.run(fetcher.prefetch(tokens, store=store_b))
    warm = drain(sched_b, sched_b.enqueue(prompt, one_tok))
    warm_s = time.perf_counter() - started
    assert adopted == len(hashes), \
        f"adopted {adopted}/{len(hashes)} fetched blocks"
    assert (list(cold.result.token_ids) == list(warm.result.token_ids)
            == list(ref.result.token_ids)), "fabric lanes diverged"

    # disagg vs mixed: one seeded schedule, two fleet shapes
    async def storm_goodput(fleet, disaggregate):
        spec = ArrivalSpec(
            name="fabric-storm",
            rate_per_min=float(os.environ.get(
                "BENCH_FABRIC_RATE_PER_MIN", "240")),
            duration_s=float(os.environ.get(
                "BENCH_FABRIC_DURATION_S", "3")),
        )
        process = ArrivalProcess(spec, seed=11)
        stack = await build_storm_stack(
            replicas=fleet, time_scale=0.2, disaggregate=disaggregate,
        )
        report = await run_storm(stack, process, drain_s=20.0)
        stack.close()
        total = report["slo"]["total"]
        return {
            "goodput_per_min": total["goodput_analyses_per_min"],
            "attainment": total["attainment"],
            "handoffs": stack.metrics.counter("fabric_disagg_handoff"),
        }

    mixed = asyncio.run(storm_goodput(
        [SyntheticReplica(f"fabric-mixed-{i}", concurrency=2,
                          time_scale=0.2) for i in range(3)],
        False,
    ))
    disagg = asyncio.run(storm_goodput(
        [SyntheticReplica("fabric-prefill-0", concurrency=2,
                          time_scale=0.2, role="prefill"),
         SyntheticReplica("fabric-decode-0", concurrency=2,
                          time_scale=0.2, role="decode"),
         SyntheticReplica("fabric-decode-1", concurrency=2,
                          time_scale=0.2, role="decode")],
        True,
    ))

    out = {
        "prompt_blocks": len(hashes),
        "ttft_cold_s": round(cold_s, 4),
        "ttft_warm_peer_s": round(warm_s, 4),
        "warm_peer_speedup": round(cold_s / warm_s, 2) if warm_s > 0 else None,
        "warm_peer_faster": bool(warm_s < cold_s),
        "fetched_ok": gen_b.metrics.counter("fabric_fetch_ok"),
        "adopted": adopted,
        "restores": gen_b.metrics.counter("kv_restore"),
        "byte_identical": True,  # asserted above; a divergence raises
        "storm_mixed": mixed,
        "storm_disagg": disagg,
        "disagg_vs_mixed_goodput": (
            round(disagg["goodput_per_min"] / mixed["goodput_per_min"], 3)
            if mixed["goodput_per_min"] else None
        ),
    }
    log(f"kv_fabric: ttft cold={out['ttft_cold_s']}s "
        f"warm-peer={out['ttft_warm_peer_s']}s "
        f"(x{out['warm_peer_speedup']}, {len(hashes)} blocks) "
        f"goodput mixed={mixed['goodput_per_min']:.0f}/min "
        f"disagg={disagg['goodput_per_min']:.0f}/min")
    return out


def bench_cold_start(params, config, tokenizer, *, slots: int, max_seq: int,
                     page_size: int, decode_block: int) -> dict:
    """Token-one latency from replica-does-not-exist (docs/SCALING.md):
    the serverless wake path the autoscaler creates when the first arrival
    lands on a fleet scaled to zero.  Each lane builds a FRESH
    BatchedGenerator (the pod-boot stand-in — params are assumed resident,
    so the number isolates program bring-up + prefill, not weight load)
    and times prompt -> first token:

    - AOT-cold: empty AOT cache directory, every serving program compiles
      live inside the measured window — the first-ever wake on a
      fingerprint;
    - AOT-warm: a second fresh generator over the now-populated cache —
      the wake the fleet actually pays once the image ships its programs.

    The split is the case for shipping the cache with the image: the
    autoscaler can only scale to zero as aggressively as
    token-one-from-zero is cheap."""
    import tempfile

    from operator_tpu.serving.engine import BatchedGenerator, SamplingParams

    prompt = ("analyse this pod failure: probe timeout after node drain; "
              "the serving fleet was scaled to zero when it arrived")
    one_tok = SamplingParams(max_tokens=1, temperature=0.0, stop_on_eos=False)

    with tempfile.TemporaryDirectory(prefix="bench-coldstart-") as aot_dir:
        def wake() -> tuple:
            started = time.perf_counter()
            generator = BatchedGenerator(
                params, config, tokenizer, max_slots=slots, max_seq=max_seq,
                paged=True, page_size=page_size, decode_block=decode_block,
                aot_cache=aot_dir,
            )
            result = generator.generate(prompt, one_tok)
            return (time.perf_counter() - started, result,
                    generator._aot.stats())

        cold_s, cold_result, cold_stats = wake()
        warm_s, warm_result, warm_stats = wake()
    assert list(cold_result.token_ids) == list(warm_result.token_ids), \
        "cold-start lanes diverged"

    return {
        # the headline: token-one from a fleet that did not exist, with
        # the image's AOT cache warm (the steady-state wake)
        "token_one_s": round(warm_s, 3),
        # first-ever wake on this fingerprint: live XLA compiles inside
        "token_one_cold_s": round(cold_s, 3),
        "aot_warm_speedup": (round(cold_s / warm_s, 2) if warm_s > 0
                             else None),
        "aot_cold": {k: cold_stats[k] for k in ("stored", "live_compiles")},
        "aot_warm": {k: warm_stats[k]
                     for k in ("hits", "live_compiles", "symbol_errors")},
    }


#: memoized probe verdict — BENCH_r03-r05 paid the 75 s probe repeatedly
#: in one run; a degraded bench should pay for the bad backend ONCE.
#: Also carries the probe forensics ("attempts", "retried", "platform")
#: the record header reports, so a degraded record shows WHY it degraded.
_PROBE_VERDICT: dict = {}


def probe_info() -> dict:
    """The probe's record-header view: verdict + attempts + whether the
    BENCH_PROBE_RETRY lane re-probed + the platform the probe saw."""
    return {
        "ok": _PROBE_VERDICT.get("ok"),
        "attempts": _PROBE_VERDICT.get("attempts", 0),
        "retried": _PROBE_VERDICT.get("retried", False),
        "platform": _PROBE_VERDICT.get("platform"),
    }


def probe_default_backend(*, force: bool = False) -> bool:
    """Check the default jax backend is healthy — in a SUBPROCESS.

    A flaky tunneled TPU plugin can either raise UNAVAILABLE *or hang
    forever* inside make_c_api_client; neither may happen in this process
    (a hung in-process init can never be interrupted and holds jax's global
    backend lock, wedging even the cpu backend).  Retries with backoff
    under ONE overall Deadline (BENCH_PROBE_DEADLINE_S, default 30 s) so a
    dead tunnel costs seconds, not the 75 s x attempts BENCH_r03-r05 paid;
    the verdict is memoized for the run (``force=True`` re-probes — used
    after waiting out an experiment-series chip hold, where the backend
    state has genuinely changed).

    Memoizing a FAILURE verbatim wedged real runs: a transient probe
    failure (the chip briefly held, the tunnel reconnecting) pinned the
    whole bench to cpu-fallback even though a later probe would have
    succeeded.  The ``BENCH_PROBE_RETRY`` lane (default on; set 0 for the
    old fail-once-degrade-forever behavior) grants a memoized *negative*
    verdict exactly ONE re-probe on the next call — a healthy backend
    recovers the run, a genuinely dead one costs one extra probe budget.
    """
    import subprocess

    from operator_tpu.utils.deadline import Deadline

    if not force and "ok" in _PROBE_VERDICT:
        retry_lane = os.environ.get("BENCH_PROBE_RETRY", "1") == "1"
        if (
            _PROBE_VERDICT["ok"]
            or not retry_lane
            or _PROBE_VERDICT.get("retried")
        ):
            return _PROBE_VERDICT["ok"]
        _PROBE_VERDICT["retried"] = True
        log("backend probe: memoized failure; BENCH_PROBE_RETRY lane "
            "re-probing once")
    retries = int(os.environ.get("BENCH_BACKEND_RETRIES", "3"))
    probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", "75"))
    budget = Deadline(float(os.environ.get("BENCH_PROBE_DEADLINE_S", "30")))
    code = "import jax; d = jax.devices(); print(d[0].platform)"
    verdict = False
    for attempt in range(retries):
        remaining = budget.remaining()
        if remaining <= 0:
            log(f"backend probe budget ({budget.total_s:.0f}s) exhausted; "
                "falling back")
            break
        try:
            out = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True, text=True,
                timeout=min(probe_timeout, remaining),
            )
            _PROBE_VERDICT["attempts"] = _PROBE_VERDICT.get("attempts", 0) + 1
            if out.returncode == 0:
                log(f"backend probe ok: {out.stdout.strip()}")
                _PROBE_VERDICT["platform"] = out.stdout.strip()
                verdict = True
                break
            log(f"backend probe failed (attempt {attempt + 1}/{retries}, "
                f"rc={out.returncode}): {out.stderr.strip().splitlines()[-1] if out.stderr.strip() else '?'}")
        except subprocess.TimeoutExpired:
            # a hang won't resolve on retry, and retrying triples the dead
            # time before the cpu fallback can produce any record at all
            _PROBE_VERDICT["attempts"] = _PROBE_VERDICT.get("attempts", 0) + 1
            log(f"backend probe hung >{budget.elapsed():.0f}s; not retrying a hang")
            break
        if attempt + 1 < retries:
            time.sleep(min(2.0 * 2**attempt, budget.remaining()))
    _PROBE_VERDICT["ok"] = verdict
    return verdict


def init_devices():
    """Initialise a jax backend without ever dying on a flaky TPU plugin.

    Order: explicit BENCH_PLATFORM override > default backend (subprocess
    health probe first, so a hung plugin can't wedge this process) > cpu
    fallback.  Returns (devices, platform_label).
    """
    import jax

    override = os.environ.get("BENCH_PLATFORM", "").strip()
    if override:
        try:
            jax.config.update("jax_platforms", override)
        except Exception:  # partially initialised jax: explicit request below
            pass
        # explicit platform request — never resolves the default backend
        devices = jax.devices(override)
        jax.config.update("jax_default_device", devices[0])
        return devices, override

    if probe_default_backend():
        devices = jax.devices()
        return devices, devices[0].platform

    # the experiment series claims the one chip for minutes at a time and
    # marks it with a RUNNING flag (scripts/tpu_experiments.sh); a bench
    # launched meanwhile (the driver's end-of-round run) would hang its
    # probe and silently degrade to CPU even though the chip is healthy —
    # wait out the live series step instead, then re-probe
    flag = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "r5_experiments", "RUNNING"
    )
    deadline = time.time() + float(os.environ.get("BENCH_WAIT_RUNNING_S", "1200"))
    waited = False
    while os.path.exists(flag) and time.time() < deadline:
        try:
            holder = int(open(flag).read().strip() or "0")
            if holder <= 0:
                break  # malformed flag (and kill(0,..) would hit the group)
            os.kill(holder, 0)  # ProcessLookupError = died without cleanup
        except PermissionError:
            pass  # alive under another uid: still holding the chip
        except (ValueError, OSError):
            break  # stale flag: nothing actually holds the chip
        if not waited:
            log("chip held by a running experiment-series step; waiting")
            waited = True
        time.sleep(10)
    if waited and probe_default_backend(force=True):
        devices = jax.devices()
        return devices, devices[0].platform

    log("default backend unavailable; falling back to cpu")
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    devices = jax.devices("cpu")
    jax.config.update("jax_default_device", devices[0])
    return devices, "cpu-fallback"


def main() -> None:
    model_name = os.environ.get("BENCH_MODEL", "tinyllama-1.1b")
    n_requests = int(os.environ.get("BENCH_REQUESTS", "32"))
    slots = int(os.environ.get("BENCH_SLOTS", "16"))
    max_tokens = int(os.environ.get("BENCH_MAX_TOKENS", "96"))
    max_seq = int(os.environ.get("BENCH_MAX_SEQ", "1024"))

    import jax
    import jax.numpy as jnp

    from operator_tpu.models import get_config, init_params
    from operator_tpu.models.tokenizer import load_tokenizer
    from operator_tpu.serving.engine import (
        BatchedGenerator, SamplingParams, ServingEngine,
    )
    from operator_tpu.serving.prompts import build_prompt

    devices, platform = init_devices()
    from operator_tpu.utils.platform import enable_persistent_compilation_cache

    cache_dir = enable_persistent_compilation_cache()
    if cache_dir:
        log(f"persistent XLA cache: {cache_dir}")
    log(f"devices ({platform}): {devices}")

    if platform == "cpu-fallback" and "BENCH_MODEL" not in os.environ:
        # insurance path: the TPU tunnel is down and no explicit model was
        # requested.  A 1.1B model on host CPU would blow the driver timeout,
        # so shrink the work to still produce a parseable (clearly degraded)
        # record instead of rc=124.
        model_name = "tiny-test"
        n_requests = min(n_requests, 8)
        max_tokens = min(max_tokens, 16)
        max_seq = min(max_seq, 512)
        log("cpu-fallback: degraded run with tiny-test model")
    log(f"model={model_name} requests={n_requests} slots={slots} "
        f"max_tokens={max_tokens} max_seq={max_seq}")

    config = get_config(model_name)
    t0 = time.perf_counter()
    # int8 is the default bench dtype (PR 10, behind the parity gate in
    # tests/test_quant_parity.py); BENCH_QUANT stays as the legacy alias
    quant = os.environ.get(
        "BENCH_INT8", os.environ.get("BENCH_QUANT", "1")
    ) == "1"
    if quant:
        # per-matrix init+quantize: never materialises the float tree, so
        # an 8B int8 bench fits the 16 GB chip (bf16 init alone would OOM)
        from operator_tpu.models.quant import init_params_quantized

        params = jax.block_until_ready(
            init_params_quantized(config, jax.random.PRNGKey(0))
        )
    else:
        # one jitted program: eager per-op dispatch compiles dozens of tiny
        # programs, which is pathologically slow over a tunneled TPU backend
        init = jax.jit(lambda key: init_params(config, key, dtype=jnp.bfloat16))
        params = jax.block_until_ready(init(jax.random.PRNGKey(0)))
    params_init_s = time.perf_counter() - t0
    log(f"params initialised in {params_init_s:.1f}s (int8={quant})")

    paged = os.environ.get("BENCH_PAGED", "1") == "1"
    decode_block = int(os.environ.get("BENCH_DECODE_BLOCK", "8"))
    # real subword tokenizer by default (VERDICT r2 weak #7: byte-level token
    # counts inflate prompts ~4x vs production BPE); BENCH_TOKENIZER may name
    # a local HF tokenizer dir, "builtin-bpe", or "byte"
    tok_spec = os.environ.get("BENCH_TOKENIZER", "builtin-bpe")
    tokenizer = load_tokenizer(tok_spec)
    if tokenizer.vocab_size > config.vocab_size:
        log(f"tokenizer vocab {tokenizer.vocab_size} exceeds model vocab "
            f"{config.vocab_size}; falling back to byte tokenizer")
        tok_spec = "byte"
        tokenizer = load_tokenizer(tok_spec)
    log(f"tokenizer: {tok_spec} (vocab {tokenizer.vocab_size})")
    # decode-ahead depth 2: one block stays in flight while the host
    # processes the previous block's tokens — hides the host<->device round
    # trip, which dominates block time over a tunneled TPU backend
    pipeline_depth = int(os.environ.get("BENCH_PIPELINE", "2"))
    # chunked prefill: bound the decode stall per admission wave
    # (BENCH_PREFILL_CHUNK=256 is the interesting open-loop comparison row)
    prefill_chunk = int(os.environ.get("BENCH_PREFILL_CHUNK", "0")) or None
    # persisted AOT executables (serving/aotcache.py): with a cache path
    # set, the bench measures bring-up TWICE — cold (compile + persist)
    # then warm on a fresh generator (deserialize only) — and serves the
    # timed phases on the warm engine, so the record carries the cold→warm
    # trajectory the autoscaling arc needs
    aot_path = os.environ.get("BENCH_AOT_CACHE", "").strip() or None
    page_size = int(os.environ.get("BENCH_PAGE_SIZE", "64"))
    prompts = [build_prompt(r) for r in build_requests(n_requests)]
    sampling = SamplingParams(max_tokens=max_tokens, temperature=0.3, stop_on_eos=False)

    # the open-loop storm now runs on cpu-fallback too (synthetic
    # replicas, compressed time scale) — the full-stack SLO record and
    # the two-replay gate are platform-independent
    open_enabled = os.environ.get("BENCH_OPEN", "1") == "1"

    # warmup: compile the decode step and every prefill bucket the timed run
    # can hit, so no XLA compile lands in the timed region.  Warm with the
    # TIMED sampling params: max_tokens feeds the truncation budget, and
    # with prefix caching the budget decides the suffix bucket — a
    # max_tokens mismatch would warm the wrong program.  One decode block
    # suffices, then cancel (slots/pages reclaimed).
    def warm_wave(generator, wave: list) -> None:
        warm_slots = generator.admit(wave, [sampling] * len(wave))
        if len(warm_slots) < len(wave):
            # page backpressure shrank the wave: the intended bucket was
            # NOT compiled — surface it instead of reporting a clean warmup
            log(f"warmup wave admitted {len(warm_slots)}/{len(wave)} rows "
                "(KV pool backpressure); its bucket stays cold")
        generator.step()  # compiles the decode block (first wave)
        # cancel-and-drain: chunk-prefilling slots are RESERVED (not yet
        # cancellable), so keep stepping the job and cancelling as slots
        # activate — leaving anything reserved would starve the next
        # admit()'s free-slot budget
        for slot in warm_slots:
            generator.cancel(slot)
        while generator.num_active:
            generator.step()
            for slot in warm_slots:
                generator.cancel(slot)

    def bring_up() -> tuple:
        """Build a generator and warm it; returns (generator,
        prefix_cached, bringup-record) — the timed unit the AOT cache
        exists to shrink."""
        t_start = time.perf_counter()
        generator = BatchedGenerator(
            params, config, tokenizer, max_slots=slots, max_seq=max_seq,
            paged=paged, page_size=page_size,
            decode_block=decode_block, pipeline_depth=pipeline_depth,
            prefill_chunk=prefill_chunk, aot_cache=aot_path,
        )
        # shared-prefix KV caching: bench prompts use the real template, so
        # its static preamble prefills once and every admission forwards
        # only its suffix — the production default (BENCH_PREFIX_CACHE=0
        # disables for A/B attribution of the win)
        prefix_cached = 0
        if paged and os.environ.get("BENCH_PREFIX_CACHE", "1") == "1":
            from operator_tpu.serving.prompts import DEFAULT_TEMPLATE

            prefix_cached = generator.set_shared_prefix(
                DEFAULT_TEMPLATE.split("{", 1)[0]
            )
            log(f"shared prefix cached: {prefix_cached} tokens")
        t_compile = time.perf_counter()
        # closed phase: full waves of `slots`, plus the remainder wave when
        # requests is not a multiple of slots
        warm_sizes = {slots}
        if n_requests % slots:
            warm_sizes.add(n_requests % slots)
        for size in sorted(warm_sizes):
            warm_wave(generator, prompts[:size])
        if open_enabled and platform != "cpu-fallback" \
                and os.environ.get("BENCH_GRID", "1") == "1":
            # open-loop phase: Poisson arrivals form waves of ANY size over
            # any prompt subset, so every (n_pad, bucket) combo — and the
            # per-size host glue — must be warm or it compiles inside a
            # measured request's latency (the r2 on-chip p99 tail).  The
            # engine's own grid precompile drives it through the real
            # admission path, restricted to the buckets THIS prompt set can
            # actually produce (chip time is the budget; all wave sizes
            # stay covered).
            grid = generator.precompile_grid(
                "serving", workload_prompts=prompts, workload_params=sampling
            )
            log(f"warmup grid: {grid}")
        now = time.perf_counter()
        aot = getattr(generator, "_aot", None)
        record = {
            "params_init_s": round(params_init_s, 2),
            "compile_s": round(now - t_compile, 2),
            "ready_s": round(now - t_start, 2),
            "aot_cache": aot.stats() if aot is not None else "off",
        }
        return generator, prefix_cached, record

    generator, prefix_cached, bringup = bring_up()
    log(f"bring-up (cold): {bringup}")
    if aot_path:
        # tear down and bring up AGAIN against the now-populated cache:
        # the warm generator (the one that serves the timed phases below)
        # should restore every program instead of compiling
        del generator
        cold = bringup
        generator, prefix_cached, bringup = bring_up()
        bringup["cold"] = cold
        log(f"bring-up (warm): ready={bringup['ready_s']}s "
            f"vs cold {cold['ready_s']}s")

    # from here on, every XLA compile is a mid-run compile: a direct,
    # multi-second p99 contribution the warmup above exists to prevent —
    # counted and reported so the discipline is visible in the record
    from operator_tpu.utils.compilewatch import CompileWatcher

    compile_watch = CompileWatcher()
    compile_watch.mark()
    degraded_storm = platform == "cpu-fallback"
    open_seconds = float(os.environ.get(
        "BENCH_OPEN_SECONDS", "10" if degraded_storm else "60"
    ))
    # compresses BOTH arrivals and synthetic service times for the CPU
    # smoke; 1.0 (real time) against a live engine
    open_time_scale = float(os.environ.get(
        "BENCH_OPEN_TIME_SCALE", "0.2" if degraded_storm else "1.0"
    ))
    loadgen_seed = int(os.environ.get("LOADGEN_SEED", "1"))
    rates = [
        float(r) for r in os.environ.get(
            "BENCH_SWEEP", os.environ.get("BENCH_RATE", "100")
        ).split(",")
    ]

    async def run() -> tuple[float, list[float], list[dict]]:
        # generous admission window -> full waves, so only warmed prefill
        # buckets are hit (any stray compile is logged by the engine)
        serving = ServingEngine(generator, admission_wait_s=0.05)
        await serving.start()
        latencies: list[float] = []

        async def one(prompt: str) -> None:
            started = time.perf_counter()
            await serving.generate(prompt, sampling)
            latencies.append(time.perf_counter() - started)

        wall_start = time.perf_counter()
        await asyncio.gather(*(one(p) for p in prompts))
        wall = time.perf_counter() - wall_start

        open_results: list[dict] = []
        if open_enabled:
            from operator_tpu.loadgen.storm import (
                EngineReplica, SyntheticReplica,
            )

            for rate in rates:
                log(f"open-loop storm: {rate:.0f} arrivals/min for "
                    f"{open_seconds:.0f}s (time x{open_time_scale})")
                if degraded_storm:
                    storm_replicas = [
                        SyntheticReplica(f"bench-replica-{i}",
                                         time_scale=open_time_scale)
                        for i in range(2)
                    ]
                else:
                    storm_replicas = [
                        EngineReplica("bench-engine", serving,
                                      max_tokens=max_tokens),
                    ]
                try:
                    result = await run_open_loop(
                        storm_replicas,
                        rate_per_min=rate, duration_s=open_seconds,
                        seed=loadgen_seed, time_scale=open_time_scale,
                        drain_s=max(30.0, open_seconds),
                    )
                except Exception as exc:
                    # a broken storm lane must FAIL LOUDLY in the record —
                    # BENCH_r04/r05 shipped a null SLO headline because the
                    # lane died silently and nothing said why
                    msg = (f"open-loop storm @{rate:.0f}/min raised "
                           f"{type(exc).__name__}: {exc}")
                    log(f"OPEN-LOOP LANE FAILED: {msg}")
                    open_results.append(
                        {"rate_per_min": rate, "error": msg}
                    )
                    continue
                log(f"open-loop @{rate:.0f}/min: "
                    f"attainment={result['attainment']} "
                    f"p50={result['p50_s']}s shed={result['shed']} "
                    f"deadline_exceeded={result['deadline_exceeded']} "
                    f"goodput={result['goodput_analyses_per_min']:.1f}/min "
                    f"replay_identical={result['replay_identical']}")
                open_results.append(result)
        await serving.close()
        return wall, latencies, open_results

    profile_dir = os.environ.get("BENCH_PROFILE", "").strip()
    if profile_dir:
        log(f"profiling timed region -> {profile_dir}")
        with generator.trace(profile_dir):
            wall, latencies, open_results = asyncio.run(run())
    else:
        wall, latencies, open_results = asyncio.run(run())
    latencies.sort()
    p50 = latencies[len(latencies) // 2]
    p99 = latencies[min(len(latencies) - 1, int(len(latencies) * 0.99))]
    per_min = n_requests / wall * 60.0
    tokens_s = n_requests * max_tokens / wall

    # mixed long-prefill + short-decode scenario, both serving modes on
    # fresh engines: the continuous scheduler's win (higher occupancy,
    # zero decode-stall steps) is measurable here without a TPU
    mixed = None
    if os.environ.get("BENCH_MIXED", "1") == "1":
        log("mixed-traffic scenario (wave vs continuous)")
        mixed = bench_mixed(
            params, config, tokenizer,
            slots=min(slots, 8), max_seq=min(max_seq, 512),
            page_size=page_size,
            decode_block=decode_block,
        )

    # KV economy: prefix-cache TTFT lanes + offload/restore + streaming
    # resume on a fresh continuous engine (CPU-measurable, like mixed)
    kv_economy = None
    if os.environ.get("BENCH_KV", "1") == "1":
        log("kv-economy scenario (prefix cache / offload / resume)")
        kv_economy = bench_kv_economy(
            params, config, tokenizer,
            slots=min(slots, 8), max_seq=min(max_seq, 512),
            page_size=page_size,
        )

    # fleet KV fabric: peer fetch vs recompute TTFT + disaggregated vs
    # mixed storm goodput (docs/FABRIC.md), CPU-measurable like kv/mixed
    kv_fabric = None
    if os.environ.get("BENCH_KV_FABRIC", "1") == "1":
        log("kv-fabric scenario (peer fetch vs recompute / disagg vs mixed)")
        kv_fabric = bench_kv_fabric(
            params, config, tokenizer,
            slots=min(slots, 8), max_seq=min(max_seq, 512),
            page_size=page_size,
        )

    # cold-start: token-one from replica-does-not-exist — the serverless
    # wake the autoscaler's scale-to-zero bets on (docs/SCALING.md)
    cold_start = None
    if os.environ.get("BENCH_COLD_START", "1") == "1":
        log("cold-start scenario (token-one from zero, AOT cold vs warm)")
        cold_start = bench_cold_start(
            params, config, tokenizer,
            slots=min(slots, 4), max_seq=min(max_seq, 512),
            page_size=page_size, decode_block=decode_block,
        )
        log(f"cold-start: token_one={cold_start['token_one_s']}s "
            f"(aot-cold {cold_start['token_one_cold_s']}s, "
            f"x{cold_start['aot_warm_speedup']})")

    # wave-engine occupancy/stall over the MAIN timed phases (the mixed
    # scenario above reports per-mode numbers on fresh engines)
    from operator_tpu.utils.timing import METRICS as _METRICS

    occupancy_stage = _METRICS.stage("batch_occupancy")
    stall_stage = _METRICS.stage("decode_stall")

    # decode MFU: ~2 FLOPs per weight per generated token (matmul-dominated,
    # attention FLOPs negligible at these sequence lengths) against the
    # chip's peak bf16 throughput (v5e: 197 TFLOP/s; override for other gens)
    from operator_tpu.models.llama import param_count

    n_params = param_count(params)
    peak_tflops = float(os.environ.get("BENCH_PEAK_TFLOPS", "197"))
    mfu = tokens_s * 2.0 * n_params / (peak_tflops * 1e12)

    log(f"wall={wall:.2f}s  p50={p50:.2f}s  p99={p99:.2f}s  "
        f"decode~{tokens_s:.0f} tok/s  throughput={per_min:.1f} expl/min")
    degraded = platform == "cpu-fallback"
    # SLO verdict from the OPEN-loop phase (the honest p50 under sustained
    # arrivals); closed-batch p50 is a queueing artifact kept for continuity.
    # A null verdict must carry its gating reason (open_loop_gate below) —
    # never the silent null of BENCH_r04/r05
    slo = None
    slo_gate_reason = None
    judged = [
        r for r in sorted(open_results, key=lambda r: r["rate_per_min"])
        if r["rate_per_min"] >= 100
    ]
    for result in judged:
        if "error" not in result and result.get("p50_s") is not None:
            slo = bool(result["p50_s"] < 2.0)
            break  # the lowest swept rate >= 100/min, regardless of input order
    if slo is None:
        if not open_enabled:
            slo_gate_reason = "BENCH_OPEN=0: storm lane disabled by env"
        elif not judged:
            slo_gate_reason = (
                f"no swept rate >= 100/min to judge "
                f"(BENCH_SWEEP/BENCH_RATE gave {rates})"
            )
        elif "error" in judged[0]:
            slo_gate_reason = judged[0]["error"]
        else:
            slo_gate_reason = (
                "zero completed analyses at >= 100/min "
                "(p50 null in every judged storm)"
            )
        log(f"open-loop SLO headline is null: {slo_gate_reason}")
    # every per-rate record carries its own judging verdict, so a reader
    # of ONE record knows whether (and why not) it fed the SLO headline
    for result in open_results:
        if "error" in result:
            result["gate"] = {"judged": False, "reason": result["error"]}
        elif result["rate_per_min"] < 100:
            result["gate"] = {
                "judged": False,
                "reason": "rate below the 100/min SLO judging floor",
            }
        elif result.get("p50_s") is None:
            result["gate"] = {
                "judged": False,
                "reason": "zero completed analyses (p50 null)",
            }
        else:
            result["gate"] = {"judged": True, "reason": None}
    # a lane that was ENABLED but produced neither records nor a gate
    # reason is the silently-dead shape BENCH_r04/r05 shipped — refuse to
    # publish it at all
    if open_enabled and not open_results and slo_gate_reason is None:
        raise SystemExit(
            "bench: open-loop lane enabled but open_loop is empty with a "
            "null open_loop_gate.reason — a silently-dead storm lane; "
            "fix the lane or disable it explicitly with BENCH_OPEN=0"
        )
    print(json.dumps({
        "metric": "explanations_per_min",
        "value": round(per_min, 1),
        "unit": "explanations/min",
        # a degraded cpu run is not a measurement against the v5e baseline
        "vs_baseline": 0.0 if degraded else round(per_min / 100.0, 3),
        "p50_latency_s": round(p50, 3),
        "p99_latency_s": round(p99, 3),
        "open_loop": open_results,
        "open_loop_p50_under_2s_at_100pm": slo,
        # why the headline above is null, when it is (never silently null)
        "open_loop_gate": {"ran": slo is not None, "reason": slo_gate_reason},
        "decode_tokens_per_s": round(tokens_s, 1),
        # end-to-end MFU incl. host/queueing time — a decode-only step MFU
        # would be higher; this is the honest number for the whole pipeline
        "decode_mfu": round(mfu, 4),
        # live decode rows / max_slots per step, and time decode rows
        # spent stalled behind phase-separated prefill dispatches —
        # the two numbers the continuous scheduler moves (docs/SERVING.md)
        "batch_occupancy_avg": (
            round(occupancy_stage.mean_ms / 100.0, 4)
            if occupancy_stage.count else None
        ),
        "decode_stall_ms_total": round(
            stall_stage.mean_ms * stall_stage.count, 1
        ),
        "mixed": mixed,
        "kv_economy": kv_economy,
        "kv_fabric": kv_fabric,
        # token-one-from-zero, AOT-warm vs AOT-cold split — the number
        # SCALE_TO_ZERO_IDLE_S trades against (docs/SCALING.md)
        "cold_start": cold_start,
        # step-clock attribution (serving/perf.py): the MEASURED decode
        # MFU decomposed per step — host-gap / device / sample-xfer
        # fractions sum to 1.0 by construction; decode_mfu here counts
        # only decode-bearing steps' attributed wall, so it upper-bounds
        # the end-to-end number above and the GAP between them is the
        # pipeline overhead the fractions attribute
        "step_attribution": generator.step_clock.summary(),
        "params_b": round(n_params / 1e9, 3),
        "peak_tflops_assumed": peak_tflops,
        "model": model_name,
        "requests": n_requests,
        "max_tokens": max_tokens,
        "decode_block": decode_block,
        "pipeline_depth": pipeline_depth,
        "tokenizer": tok_spec,
        "weight_dtype": "int8" if quant else "bf16",
        # structured bring-up record (cold→warm trajectory when
        # BENCH_AOT_CACHE is set; "off" aot_cache otherwise)
        "bringup": bringup,
        "prefix_cached_tokens": prefix_cached,
        "midrun_compiles": compile_watch.count_since_mark(),
        "platform": platform,
        # which backend the subprocess probe chose and how hard it had to
        # try (incl. the BENCH_PROBE_RETRY lane) — a degraded record now
        # carries its own explanation
        "backend_probe": probe_info(),
        "degraded": degraded,
    }))


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # never leave the driver with an unparseable traceback
        import traceback

        traceback.print_exc(file=sys.stderr)
        print(json.dumps({
            "metric": "explanations_per_min",
            "value": 0.0,
            "unit": "explanations/min",
            "vs_baseline": 0.0,
            "error": f"{type(e).__name__}: {e}",
        }))
        sys.exit(1)
