"""operator_tpu — a TPU-native rebuild of the Podmortem system.

The reference (podmortem/operator, see SURVEY.md) is a Kubernetes operator that
watches pods for failures, collects logs/events, pattern-matches them against
Git-synced pattern libraries and produces AI explanations via two external
REST services (log-parser, ai-interface).  This framework re-implements the
whole system in one tree with the compute running on TPU:

- ``operator_tpu.schema``    — typed CR/analysis/pattern models (replaces the
  external ``common-lib`` Maven artifact and the three CRD YAMLs).
- ``operator_tpu.patterns``  — the pattern-match engine (replaces the external
  ``log-parser`` service), with a CPU scorer and a TPU semantic path.
- ``operator_tpu.models``    — JAX implementations of the LLMs and encoders
  (TinyLlama-1.1B → Llama-3-8B / Mistral-7B, all-MiniLM-L6).
- ``operator_tpu.ops``       — Pallas TPU kernels (similarity top-k, ragged
  paged attention) with pure-XLA reference implementations.
- ``operator_tpu.parallel``  — device mesh / sharding layer (DP/TP/FSDP over
  ICI via jax.sharding + shard_map).
- ``operator_tpu.serving``   — continuous-batching inference engine (replaces
  the external ``ai-interface`` service).
- ``operator_tpu.operator``  — the asyncio control plane: watch loop,
  reconcilers, event emission, durable storage, git pattern sync, health.
- ``operator_tpu.utils``     — config, timing/metrics, logging.

Nothing here imports jax at package-import time; the control plane can run on
a machine with no accelerator, and the data plane initialises lazily.
"""

__version__ = "0.1.0"
