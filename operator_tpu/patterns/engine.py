"""PatternEngine — the analysis facade (the log-parser service's role).

``analyze(PodFailureData) -> AnalysisResult`` is the behavioural equivalent
of the reference's ``POST /parse`` (LogParserRestClient.java:37-39), run
in-process.  Evidence beyond the raw log also participates in matching,
which the reference's operator merely forwarded:

- container termination states (exit code / reason / message,
  PodFailureWatcher.java:147-159 detects them but never matches on them)
  become synthetic evidence lines like
  ``[container-status] app terminated exit code 137 reason=OOMKilled``;
- Kubernetes event notes collected with the failure
  (PodFailureWatcher.java:326-332) are matched as
  ``[k8s-event] Warning BackOff: ...`` lines.

A reload() picks up newly synced pattern libraries; the sync reconciler
calls it after each git pull.
"""

from __future__ import annotations

import logging
import threading
import time
import uuid
from typing import Optional

from ..schema.analysis import AnalysisResult, PodFailureData, StageTimings
from ..schema.kube import Pod
from .loader import LoadedLibrary, load_builtin_library, load_libraries
from .matcher import MatcherConfig, collect_events, fold_events
from .prefilter import LiteralPrefilter
from .semantic import SemanticMatcher
from .windows import split_lines

log = logging.getLogger(__name__)


def status_evidence_lines(pod: Optional[Pod]) -> list[str]:
    """Synthetic evidence lines derived from the pod's container statuses."""
    if pod is None or pod.status is None:
        return []
    lines: list[str] = []
    for cs in [*pod.status.container_statuses, *pod.status.init_container_statuses]:
        for label, state in (("state", cs.state), ("lastState", cs.last_state)):
            if state is None:
                continue
            if state.terminated is not None:
                t = state.terminated
                parts = [f"[container-status] {cs.name} terminated"]
                if t.exit_code is not None:
                    parts.append(f"exit code {t.exit_code}")
                if t.reason:
                    parts.append(f"reason={t.reason}")
                if t.message:
                    parts.append(t.message)
                lines.append(" ".join(parts))
            if state.waiting is not None and state.waiting.reason:
                msg = state.waiting.message or ""
                lines.append(f"[container-status] {cs.name} waiting reason={state.waiting.reason} {msg}".rstrip())
        if cs.restart_count:
            lines.append(f"[container-status] {cs.name} restartCount={cs.restart_count}")
    return lines


def event_evidence_lines(failure: PodFailureData) -> list[str]:
    lines = []
    for event in failure.events:
        note = event.note or ""
        lines.append(f"[k8s-event] {event.type_ or 'Normal'} {event.reason or ''}: {note}".rstrip())
    return lines


class PatternEngine:
    """Thread-safe holder of loaded libraries + the match entry point.

    The control plane calls :meth:`analyze` per failure and
    :meth:`reload` after every pattern sync; both may race, hence the lock
    around the library snapshot (the reference relies on the parser service
    re-reading the PVC per request — we reload explicitly instead).
    """

    def __init__(
        self,
        cache_dir: Optional[str] = None,
        *,
        enabled_libraries: Optional[list[str]] = None,
        include_builtin: bool = True,
        config: Optional[MatcherConfig] = None,
        semantic: "SemanticMatcher | bool | None" = None,
        prefilter: bool = True,
    ) -> None:
        self.cache_dir = cache_dir
        self.enabled_libraries = enabled_libraries
        self.include_builtin = include_builtin
        self.config = config or MatcherConfig()
        if semantic is True:
            semantic = SemanticMatcher()
        self.semantic: Optional[SemanticMatcher] = semantic or None
        self._use_prefilter = prefilter
        self.prefilter: Optional[LiteralPrefilter] = None
        self._lock = threading.Lock()
        self._libraries: list[LoadedLibrary] = []
        self.reload()

    # ------------------------------------------------------------------
    def reload(self) -> int:
        """Re-scan the cache dir; returns the number of loaded patterns."""
        libraries: list[LoadedLibrary] = []
        if self.cache_dir:
            libraries.extend(load_libraries(self.cache_dir, self.enabled_libraries))
        if self.include_builtin:
            builtin = load_builtin_library()
            # synced libraries shadow the builtin one by name
            if all(lib.name != builtin.name for lib in libraries):
                libraries.append(builtin)
        with self._lock:
            self._libraries = libraries
        if self._use_prefilter:
            # rebuild the native literal automaton for the new pattern set
            all_patterns = [p for lib in libraries for p in lib.patterns]
            self.prefilter = LiteralPrefilter(all_patterns)
            log.info(
                "literal prefilter: %d anchored / %d full-scan (native=%s)",
                self.prefilter.num_anchored, len(self.prefilter.full_scan_ids),
                self.prefilter.native,
            )
        if self.semantic is not None:
            # the embedding-cache build step of the sync reconciler
            # (SURVEY.md §7 stage 3): re-embed anchors after every git pull
            self.semantic.rebuild(libraries)
        total = sum(len(lib.patterns) for lib in libraries)
        log.info("pattern engine loaded %d libraries / %d patterns", len(libraries), total)
        return total

    @property
    def libraries(self) -> list[LoadedLibrary]:
        with self._lock:
            return list(self._libraries)

    def library_names(self) -> list[str]:
        return sorted(lib.name for lib in self.libraries)

    # ------------------------------------------------------------------
    def analyze(self, failure: PodFailureData) -> AnalysisResult:
        started = time.perf_counter()
        lines = split_lines(failure.logs)
        lines.extend(event_evidence_lines(failure))
        lines.extend(status_evidence_lines(failure.pod))
        pod = failure.pod
        # collect the UNtruncated regex/keyword hits first so the semantic
        # merge dedupes and summarises over the full set — one fold at the
        # end ranks/truncates exactly once
        events = collect_events(self.libraries, lines, self.config, prefilter=self.prefilter)
        if self.semantic is not None and self.semantic.num_patterns:
            # semantic catches what regex missed; a pattern already hit by
            # its regex keeps the (higher-precision) regex event only
            matched_ids = {e.matched_pattern.id for e in events}
            events.extend(
                e
                for e in self.semantic.match(lines)
                if e.matched_pattern.id not in matched_ids
            )
        summary, folded = fold_events(events, self.config)
        result = AnalysisResult(
            analysis_id=str(uuid.uuid4()),
            pod_name=pod.metadata.name if pod else None,
            pod_namespace=pod.metadata.namespace if pod else None,
            summary=summary,
            events=folded,
        )
        result.timings = StageTimings(parse_ms=round((time.perf_counter() - started) * 1e3, 3))
        return result


def _main(argv: Optional[list[str]] = None) -> int:
    """``python -m operator_tpu.patterns.engine [logfile ...]`` — analyze log
    files (or stdin) against the loaded pattern libraries and print the
    result as YAML."""
    import argparse
    import sys

    import yaml

    parser = argparse.ArgumentParser(
        prog="operator_tpu.patterns.engine",
        description="Pattern-match log files against failure-pattern libraries.",
    )
    parser.add_argument("logfiles", nargs="*", help="log files (default: stdin)")
    parser.add_argument("--cache-dir", help="synced pattern-cache directory")
    parser.add_argument("--no-builtin", action="store_true",
                        help="skip the built-in kubernetes-common library")
    parser.add_argument("--top", type=int, default=5, help="show top-K events")
    args = parser.parse_args(argv)

    engine = PatternEngine(cache_dir=args.cache_dir, include_builtin=not args.no_builtin)
    sources = args.logfiles or ["-"]
    exit_code = 0
    for source in sources:
        try:
            logs = sys.stdin.read() if source == "-" else open(source, encoding="utf-8", errors="replace").read()
        except OSError as exc:
            print(f"error: cannot read {source}: {exc}", file=sys.stderr)
            exit_code = 2
            continue
        result = engine.analyze(PodFailureData(logs=logs))
        doc = {
            "source": source,
            "summary": result.summary.__dict__,
            "events": [
                {
                    "pattern": e.matched_pattern.id,
                    "name": e.matched_pattern.name,
                    "severity": e.matched_pattern.severity,
                    "score": e.score,
                    "line": e.context.line_number if e.context else None,
                    "matched": e.context.matched_line if e.context else None,
                }
                for e in result.top_events(args.top)
            ],
        }
        try:
            print(yaml.safe_dump(doc, sort_keys=False), end="")
        except BrokenPipeError:
            sys.stderr.close()
            return 0
    return exit_code


if __name__ == "__main__":
    raise SystemExit(_main())
