"""CLI entry: ``python -m operator_tpu.patterns [logfile ...]``."""

from .engine import _main

if __name__ == "__main__":
    raise SystemExit(_main())
