"""CPU pattern matcher: regex/keyword scoring of log lines.

This is the in-tree replacement for the reference's external log-parser
service (``POST /parse``: PodFailureData -> AnalysisResult, reference
LogParserRestClient.java:37-39).  Scoring model:

- a line matching a pattern's primary regex (or containing all its keywords)
  scores ``confidence``;
- each secondary pattern found within ``proximity_window`` lines of the hit
  adds its ``weight`` (corroboration);
- an event is *significant* when its score clears ``significance_threshold``
  (drives ``summary.significantEvents``, which the reference surfaces in
  K8s events — EventService.java:75-78).

Repeated hits of one pattern (crash loops replay the same error) are capped
at ``max_events_per_pattern``, keeping the newest hits because failure
evidence concentrates at the log tail.
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass
from typing import TYPE_CHECKING, Optional

if TYPE_CHECKING:  # pragma: no cover - typing only
    from .prefilter import LiteralPrefilter

from ..schema.analysis import (
    AnalysisEvent,
    AnalysisResult,
    AnalysisSummary,
    MatchContext,
    MatchedPattern,
    Severity,
)
from ..schema.patterns import Pattern
from .loader import LoadedLibrary
from .windows import context_window

DEFAULT_SIGNIFICANCE_THRESHOLD = 0.7
DEFAULT_MAX_EVENTS_PER_PATTERN = 3


@dataclass
class MatcherConfig:
    significance_threshold: float = DEFAULT_SIGNIFICANCE_THRESHOLD
    max_events_per_pattern: int = DEFAULT_MAX_EVENTS_PER_PATTERN
    max_total_events: int = 50


def _primary_hits(
    pattern: Pattern,
    lines: list[str],
    candidate_lines: Optional[list[int]] = None,
) -> list[int]:
    """Line numbers where the primary pattern fires.

    ``candidate_lines`` (ascending) restricts the scan to lines the literal
    prefilter already flagged (prefilter.py) — pure work-skipping; the
    prefilter guarantees no match exists outside the candidates."""
    primary = pattern.primary_pattern
    if primary is None:
        return []
    line_numbers = candidate_lines if candidate_lines is not None else range(len(lines))
    hits: list[int] = []
    regex = primary.compiled()
    if regex is not None:
        for i in line_numbers:
            if regex.search(lines[i]):
                hits.append(i)
    elif primary.keywords:
        lowered = [kw.lower() for kw in primary.keywords]
        for i in line_numbers:
            hay = lines[i].lower()
            if all(kw in hay for kw in lowered):
                hits.append(i)
    return hits


def _secondary_bonus(pattern: Pattern, lines: list[str], hit_line: int) -> float:
    bonus = 0.0
    for secondary in pattern.secondary_patterns:
        regex = secondary.compiled()
        if regex is None:
            continue
        lo = max(0, hit_line - secondary.proximity_window)
        hi = min(len(lines), hit_line + secondary.proximity_window + 1)
        for i in range(lo, hi):
            if i != hit_line and regex.search(lines[i]):
                bonus += secondary.weight
                break  # each secondary corroborates at most once
    return bonus


def match_pattern(
    pattern: Pattern,
    lines: list[str],
    config: Optional[MatcherConfig] = None,
    source: str = "regex",
    candidate_lines: Optional[list[int]] = None,
) -> list[AnalysisEvent]:
    config = config or MatcherConfig()
    if config.max_events_per_pattern <= 0:
        return []
    hits = _primary_hits(pattern, lines, candidate_lines)
    if not hits:
        return []
    # newest hits carry the evidence; cap per pattern
    hits = hits[-config.max_events_per_pattern :]
    confidence = pattern.primary_pattern.confidence if pattern.primary_pattern else 1.0
    extraction = pattern.context_extraction
    events = []
    for line_number in hits:
        score = confidence + _secondary_bonus(pattern, lines, line_number)
        before, after = context_window(
            lines,
            line_number,
            before=extraction.lines_before,
            after=extraction.lines_after,
        )
        remediation = pattern.remediation.description if pattern.remediation else None
        events.append(
            AnalysisEvent(
                score=round(score, 4),
                source=source,
                matched_pattern=MatchedPattern(
                    id=pattern.id,
                    name=pattern.name or pattern.id,
                    severity=pattern.severity_enum.value,
                    category=pattern.category,
                    remediation=remediation,
                ),
                context=MatchContext(
                    line_number=line_number,
                    matched_line=lines[line_number],
                    lines_before=before,
                    lines_after=after,
                ),
            )
        )
    return events


def summarize(events: list[AnalysisEvent], config: Optional[MatcherConfig] = None) -> AnalysisSummary:
    config = config or MatcherConfig()
    if not events:
        return AnalysisSummary(highest_severity=None, significant_events=0, total_events=0, score=0.0)
    significant = [e for e in events if e.score >= config.significance_threshold]
    highest = Severity.highest([e.severity for e in (significant or events)])
    return AnalysisSummary(
        highest_severity=highest.value,
        significant_events=len(significant),
        total_events=len(events),
        score=round(max(e.score for e in events), 4),
    )


def fold_events(
    events: list[AnalysisEvent], config: Optional[MatcherConfig] = None
) -> tuple[AnalysisSummary, list[AnalysisEvent]]:
    """The one ranking policy: sort by (score, severity), summarise over the
    FULL set, then truncate.  Shared by the regex fold and the semantic
    merge so both paths rank identically."""
    config = config or MatcherConfig()
    events = sorted(events, key=lambda e: (e.score, e.severity.rank), reverse=True)
    summary = summarize(events, config)
    return summary, events[: config.max_total_events]


def collect_events(
    libraries: list[LoadedLibrary],
    lines: list[str],
    config: Optional[MatcherConfig] = None,
    prefilter: Optional["LiteralPrefilter"] = None,
) -> list[AnalysisEvent]:
    """Score every pattern of every library against the log lines; returns
    the UNtruncated event list so callers can merge other sources (e.g. the
    semantic matcher) before the single fold_events ranking pass.

    With a prefilter, anchored patterns only regex-scan the lines the
    native literal scan flagged; unanchored ones scan everything."""
    config = config or MatcherConfig()
    candidates = prefilter.candidate_lines(lines) if prefilter is not None else None
    events: list[AnalysisEvent] = []
    for library in libraries:
        for pattern in library.patterns:
            candidate_lines = None
            if candidates is not None and pattern.id not in prefilter.full_scan_ids:
                flagged = candidates.get(pattern.id)
                if not flagged:
                    continue  # literal absent -> pattern cannot match
                candidate_lines = sorted(flagged)
            events.extend(
                match_pattern(pattern, lines, config, candidate_lines=candidate_lines)
            )
    return events


def match_libraries(
    libraries: list[LoadedLibrary],
    lines: list[str],
    config: Optional[MatcherConfig] = None,
    *,
    pod_name: Optional[str] = None,
    pod_namespace: Optional[str] = None,
) -> AnalysisResult:
    """Score every pattern of every library against the log lines and fold
    the hits into one AnalysisResult (highest-scoring events first)."""
    config = config or MatcherConfig()
    summary, events = fold_events(collect_events(libraries, lines, config), config)
    return AnalysisResult(
        analysis_id=str(uuid.uuid4()),
        pod_name=pod_name,
        pod_namespace=pod_namespace,
        summary=summary,
        events=events,
    )
