"""Literal prefilter: one native scan decides which (pattern, line) pairs
deserve a real regex check.

The matcher's hot loop is O(patterns × lines) Python regex calls
(matcher.py _primary_hits) — the analysis-latency bearing stage between
kube watch and the TPU programs.  Most library patterns anchor on a
distinctive literal ("OutOfMemoryError", "CrashLoopBackOff", "exit code"):
scanning the whole log ONCE for all such literals (native/logscan.cpp
Aho-Corasick via operator_tpu.native) yields candidate lines per pattern,
and only those lines see the full regex.  Patterns whose regex has no
required literal (alternations, classes, quantifiers) are conservatively
left on the full scan path — the prefilter NEVER changes results, only
skips work (guaranteed by test_prefilter.py's equivalence tests).
"""

from __future__ import annotations

import logging
from bisect import bisect_left
from typing import Optional

from ..native import MultiPatternScanner
from ..schema.patterns import Pattern

log = logging.getLogger(__name__)

MIN_LITERAL_LEN = 4

#: zero-width / class escapes — not literal characters
_NONLITERAL_ESCAPES = set("dDwWsSbBAZ")
#: single-char escapes that decode to a real in-line character
_CHAR_ESCAPES = {"t": "\t", "f": "\f", "v": "\v", "a": "\a"}
#: escapes for characters that never occur inside a splitlines() line —
#: a per-line match can't contain them, so they just close the run
_LINEBREAK_ESCAPES = set("nr")
#: numeric / named escapes (\xHH, \uHHHH, \UHHHHHHHH, \N{...}) — bail
#: rather than guess the decoded character
_OPAQUE_ESCAPES = set("xuUN")
_QUANTIFIER_START = set("*+?{")


def _skip_group(regex: str, i: int) -> Optional[int]:
    """i points at '('; returns index past the matching ')' or None."""
    depth = 0
    while i < len(regex):
        ch = regex[i]
        if ch == "\\":
            i += 2
            continue
        if ch == "[":
            end = _skip_class(regex, i)
            if end is None:
                return None
            i = end
            continue
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                return i + 1
        i += 1
    return None


def _skip_class(regex: str, i: int) -> Optional[int]:
    """i points at '['; returns index past the matching ']' or None."""
    i += 1
    if i < len(regex) and regex[i] == "^":
        i += 1
    if i < len(regex) and regex[i] == "]":  # leading ] is literal
        i += 1
    while i < len(regex):
        if regex[i] == "\\":
            i += 2
            continue
        if regex[i] == "]":
            return i + 1
        i += 1
    return None


def _skip_quantifier(regex: str, i: int) -> Optional[int]:
    """Skip a quantifier at i (if any); None on an unterminated '{'."""
    if i < len(regex) and regex[i] in "*+?":
        i += 1
    elif i < len(regex) and regex[i] == "{":
        end = regex.find("}", i)
        if end < 0:
            return None
        i = end + 1
    else:
        return i
    if i < len(regex) and regex[i] == "?":  # non-greedy marker
        i += 1
    return i


def _split_alternation(regex: str) -> Optional[list[str]]:
    """Split on top-level '|' (respecting groups/classes/escapes)."""
    branches: list[str] = []
    start = 0
    i = 0
    while i < len(regex):
        ch = regex[i]
        if ch == "\\":
            i += 2
            continue
        if ch == "(":
            end = _skip_group(regex, i)
            if end is None:
                return None
            i = end
            continue
        if ch == "[":
            end = _skip_class(regex, i)
            if end is None:
                return None
            i = end
            continue
        if ch == "|":
            branches.append(regex[start:i])
            start = i + 1
        i += 1
    branches.append(regex[start:])
    return branches


def _branch_runs(branch: str) -> Optional[list[str]]:
    """Maximal literal runs every match of ``branch`` must contain.

    A quantified element is dropped from its run (may repeat/vanish);
    groups and classes close the current run but what's OUTSIDE them stays
    required.  None -> unanalyzable (lookarounds, backrefs, bad syntax)."""
    runs: list[str] = []
    current: list[str] = []

    def close() -> None:
        if current:
            runs.append("".join(current))
            current.clear()

    i = 0
    while i < len(branch):
        ch = branch[i]
        if ch == "\\":
            if i + 1 >= len(branch):
                return None
            escaped = branch[i + 1]
            if escaped.isdigit():  # backreference / octal
                return None
            if escaped in _OPAQUE_ESCAPES:  # \xHH, \uHHHH, \N{...}: don't guess
                return None
            after = i + 2
            if escaped in _NONLITERAL_ESCAPES or escaped in _LINEBREAK_ESCAPES:
                close()
                end = _skip_quantifier(branch, after)
                if end is None:
                    return None
                i = end
                continue
            literal_char = _CHAR_ESCAPES.get(escaped)
            if literal_char is None:
                if escaped.isalnum():  # unrecognized alphanumeric escape
                    return None
                literal_char = escaped  # escaped punctuation: \. \( \\ ...
            end = _skip_quantifier(branch, after)
            if end is None:
                return None
            if end != after:  # quantified literal: can't require it
                close()
            else:
                current.append(literal_char)
            i = end
            continue
        if ch == "(":
            if branch.startswith("(?", i) and not branch.startswith("(?:", i):
                return None  # lookaround / inline flag mid-pattern
            end = _skip_group(branch, i)
            if end is None:
                return None
            close()
            end = _skip_quantifier(branch, end)
            if end is None:
                return None
            i = end
            continue
        if ch == "[":
            end = _skip_class(branch, i)
            if end is None:
                return None
            close()
            end = _skip_quantifier(branch, end)
            if end is None:
                return None
            i = end
            continue
        if ch == ".":
            close()
            end = _skip_quantifier(branch, i + 1)
            if end is None:
                return None
            i = end
            continue
        if ch in "^$":
            close()
            i += 1
            continue
        if ch in _QUANTIFIER_START:
            # quantifier applying to the previous literal char: that char
            # may repeat or vanish — drop it and close the run
            if current:
                current.pop()
            close()
            end = _skip_quantifier(branch, i)
            if end is None or end == i:
                return None
            i = end
            continue
        if ch == "|":  # should have been split already
            return None
        # literal char — but only required if not quantified
        nxt = i + 1
        if nxt < len(branch) and branch[nxt] in _QUANTIFIER_START:
            end = _skip_quantifier(branch, nxt)
            if end is None:
                return None
            close()
            i = end
            continue
        current.append(ch)
        i += 1
    close()
    return runs


def _unwrap(regex: str) -> str:
    """Strip a group that wraps the entire pattern: ``(a|b)`` -> ``a|b``."""
    while regex.startswith("(") and not (
        regex.startswith("(?") and not regex.startswith("(?:")
    ):
        end = _skip_group(regex, 0)
        if end != len(regex):
            return regex
        regex = regex[3:-1] if regex.startswith("(?:") else regex[1:-1]
    return regex


def required_literals(regex: str) -> Optional[tuple[list[str], bool]]:
    """(literals, case_insensitive) such that every match of ``regex``
    contains at least ONE of the literals; None if no such set is provable.

    ``(?i)(OOMKilled|Out of memory|oom-kill)`` -> those three, ci;
    ``java\\.lang\\.OutOfMemoryError(: .*)?`` -> the class name, cs."""
    case_insensitive = False
    if regex.startswith("(?i)"):
        case_insensitive = True
        regex = regex[4:]
    branches = _split_alternation(_unwrap(regex))
    if branches is None:
        return None
    literals: list[str] = []
    for branch in branches:
        runs = _branch_runs(branch)
        if runs is None:
            return None
        best = max((r for r in runs if len(r) >= MIN_LITERAL_LEN), key=len, default=None)
        if best is None:
            return None  # a match could ride this branch with no literal
        literals.append(best.lower() if case_insensitive else best)
    return literals, case_insensitive


def literals_for_pattern(pattern: Pattern) -> Optional[tuple[list[str], bool]]:
    """(literals, case_insensitive) guaranteeing: the pattern can only fire
    on a line containing >=1 of the literals.  None -> full scan."""
    primary = pattern.primary_pattern
    if primary is None:
        return None
    if primary.regex:
        return required_literals(primary.regex)
    if primary.keywords:
        # every keyword must appear; anchor on the longest (rarest) one
        longest = max(primary.keywords, key=len)
        if len(longest) >= MIN_LITERAL_LEN:
            return [longest.lower()], True
        return None
    return None


class LiteralPrefilter:
    """Built per pattern-set (engine reload); applied per failure log."""

    def __init__(self, patterns: list[Pattern]) -> None:
        self.full_scan_ids: set[str] = set()
        cs_literals: list[bytes] = []
        ci_literals: list[bytes] = []
        self._cs_owner: list[str] = []  # literal idx -> pattern id
        self._ci_owner: list[str] = []
        for pattern in patterns:
            anchored = literals_for_pattern(pattern)
            if anchored is None:
                self.full_scan_ids.add(pattern.id)
                continue
            literals, case_insensitive = anchored
            if case_insensitive and not all(lit.isascii() for lit in literals):
                # the ci scan lowercases BYTES (ASCII-only) but literals are
                # lowercased as str (full Unicode); for non-ASCII letters the
                # two disagree and the literal may silently never be found —
                # conservative: full scan for the whole pattern
                self.full_scan_ids.add(pattern.id)
                continue
            for literal in literals:
                if case_insensitive:
                    ci_literals.append(literal.encode("utf-8", "surrogateescape"))
                    self._ci_owner.append(pattern.id)
                else:
                    cs_literals.append(literal.encode("utf-8", "surrogateescape"))
                    self._cs_owner.append(pattern.id)
        self._cs = MultiPatternScanner(cs_literals) if cs_literals else None
        self._ci = MultiPatternScanner(ci_literals) if ci_literals else None
        self.native = bool(
            (self._cs and self._cs.native) or (self._ci and self._ci.native)
        )
        self.num_anchored = len(patterns) - len(self.full_scan_ids)

    def candidate_lines(self, lines: list[str]) -> dict[str, set[int]]:
        """pattern id -> line numbers that may match.  Patterns in
        ``full_scan_ids`` are absent — callers scan those fully."""
        import numpy as np

        text = "\n".join(lines).encode("utf-8", "surrogateescape")
        # vectorised byte-offset -> line-number mapping
        newline_at = np.flatnonzero(np.frombuffer(text, np.uint8) == 0x0A)
        starts = np.concatenate([[0], newline_at + 1])

        candidates: dict[str, set[int]] = {}

        def collect(scanner, owners, buf: bytes) -> None:
            ids, end_offsets = scanner.scan_arrays(buf)
            if len(ids) == 0:
                return
            line_numbers = np.searchsorted(starts, end_offsets, side="right") - 1
            for literal_id, line_number in zip(ids.tolist(), line_numbers.tolist()):
                candidates.setdefault(owners[literal_id], set()).add(line_number)

        if self._cs is not None:
            collect(self._cs, self._cs_owner, text)
        if self._ci is not None:
            collect(self._ci, self._ci_owner, text.lower())
        return candidates
