"""Pattern-library loading from the synced cache directory.

Directory contract (reference PatternSyncService.java:42-58): the sync
reconciler materialises each Git repo at
``<cache>/<library-cr-name>/<repo-name>/``; every ``*.yaml|*.yml`` anywhere
under the cache is one pattern library named after its file stem
(reference PatternSyncService.getAvailableLibraries :88-114).

Robustness the reference can't have (its parser is an unseen sibling):
patterns with malformed regexes are skipped with a warning at load time
instead of blowing up the match path.
"""

from __future__ import annotations

import logging
import os
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Optional

from ..schema.patterns import Pattern, PatternLibraryFile

log = logging.getLogger(__name__)

_YAML_EXTS = (".yaml", ".yml")


@dataclass
class LoadedLibrary:
    """One validated pattern library ready for matching."""

    name: str
    path: Optional[str] = None
    patterns: list[Pattern] = field(default_factory=list)
    skipped: int = 0  # patterns dropped for malformed regexes


def discover_library_files(cache_dir: str | Path) -> list[Path]:
    """All pattern YAML files under the cache, sorted for determinism
    (reference walks with Files.walk, PatternSyncService.java:94-107)."""
    root = Path(cache_dir)
    if not root.is_dir():
        return []
    return sorted(
        p for p in root.rglob("*") if p.is_file() and p.suffix.lower() in _YAML_EXTS
    )


def available_libraries(cache_dir: str | Path) -> list[str]:
    """Advertised library names: ``metadata.library_id`` when declared, else
    the file stem (the reference only knows stems —
    PatternSyncService.java:94-107; we honour the declared id so the name a
    user sees in status is the name that works in ``enabledLibraries``)."""
    names = set()
    for path in discover_library_files(cache_dir):
        names.add(load_library_file(path).name)
    return sorted(names)


def _validate_pattern(pattern: Pattern, source: str) -> bool:
    """Compile every regex once; reject the pattern if any is malformed or if
    it has no matchable primary at all."""
    primary = pattern.primary_pattern
    if primary is None or (not primary.regex and not primary.keywords):
        log.warning("pattern %r in %s has no primary regex/keywords; skipping",
                    pattern.id or pattern.name, source)
        return False
    try:
        primary.compiled()
        for secondary in pattern.secondary_patterns:
            secondary.compiled()
    except re.error as exc:
        log.warning("pattern %r in %s has malformed regex (%s); skipping",
                    pattern.id or pattern.name, source, exc)
        return False
    return True


def load_library_file(path: str | Path) -> LoadedLibrary:
    path = Path(path)
    try:
        parsed = PatternLibraryFile.load(path)
    except Exception as exc:  # malformed YAML: empty library, not a crash
        log.warning("failed to load pattern library %s: %s", path, exc)
        return LoadedLibrary(name=path.stem, path=str(path), patterns=[], skipped=0)
    kept, skipped = [], 0
    for pattern in parsed.patterns:
        if _validate_pattern(pattern, str(path)):
            kept.append(pattern)
        else:
            skipped += 1
    return LoadedLibrary(
        name=parsed.metadata.library_id or path.stem,
        path=str(path),
        patterns=kept,
        skipped=skipped,
    )


def load_libraries(
    cache_dir: str | Path,
    enabled: Optional[Iterable[str]] = None,
) -> list[LoadedLibrary]:
    """Load every library under the cache; ``enabled`` (from
    PatternLibrary.spec.enabledLibraries, patternlibrary-crd.yaml:46-50)
    filters by the advertised library name (``metadata.library_id`` or file
    stem) when non-empty."""
    enabled_set = {e for e in enabled} if enabled else None
    libraries = []
    for path in discover_library_files(cache_dir):
        lib = load_library_file(path)
        if enabled_set is not None and lib.name not in enabled_set and path.stem not in enabled_set:
            continue
        if lib.patterns or lib.skipped:
            libraries.append(lib)
    return libraries


def builtin_library_path() -> str:
    """The pattern library shipped with the framework (common Kubernetes /
    JVM / Python failure modes) — used when no PatternLibrary CR is synced."""
    return os.path.join(os.path.dirname(__file__), "builtin", "kubernetes-common.yaml")


def load_builtin_library() -> LoadedLibrary:
    return load_library_file(builtin_library_path())
