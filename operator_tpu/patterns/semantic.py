"""Semantic pattern matching: embedding similarity over log windows.

The regex matcher (matcher.py) only fires on patterns whose exact regex or
keywords appear; the semantic path catches failures phrased differently —
it embeds every log window and every pattern's anchor text into one vector
space and scores ``windows @ patterns.T`` on the MXU
(ops/similarity.py's fused best-window kernel on TPU).

Two embedders, one interface:

- :class:`HashingEmbedder` — deterministic char-n-gram feature hashing,
  zero weights, pure numpy.  Lexical-overlap similarity; always available
  (this repo runs with zero egress, so a downloaded checkpoint can never
  be a hard dependency).
- :class:`NeuralEmbedder` — the JAX MiniLM-class encoder
  (models/encoder.py), used when a local checkpoint directory is
  configured.  True semantic similarity, runs on TPU.

Pattern embeddings are (re)built on ``reload`` after every git pattern
sync — this is the "pattern cache → embedding cache build step hooked into
the sync reconciler" of SURVEY.md §7 stage 3.
"""

from __future__ import annotations

import logging
import re
import zlib
from typing import Optional, Protocol, Sequence

import numpy as np

from ..schema.analysis import AnalysisEvent, MatchContext, MatchedPattern
from ..schema.patterns import Pattern
from .loader import LoadedLibrary
from .windows import LogWindow, iter_windows

log = logging.getLogger(__name__)

DEFAULT_WINDOW_LINES = 16
DEFAULT_STRIDE = 8


class Embedder(Protocol):
    """Text -> L2-normalised embeddings [N, dim]."""

    dim: int

    def embed(self, texts: Sequence[str]) -> np.ndarray: ...


_REGEX_TOKEN = re.compile(r"[A-Za-z][A-Za-z0-9_.]{2,}")


def regex_literals(regex: Optional[str]) -> list[str]:
    """Literal word-ish tokens inside a regex (``java\\.lang\\.OutOfMemoryError``
    -> ``java lang OutOfMemoryError``) — the vocabulary the pattern expects
    to see in real log lines."""
    if not regex:
        return []
    cleaned = regex.replace("\\.", " ").replace("\\", " ")
    return [t for t in _REGEX_TOKEN.findall(cleaned) if t.lower() not in {"the", "and"}]


def embedding_text(pattern: Pattern) -> str:
    """What gets embedded for a pattern: the natural-language anchor plus
    the literal vocabulary of its regexes/keywords, so lexical embedders
    see log-shaped tokens and neural embedders see the description."""
    parts = [pattern.anchor_text()]
    if pattern.primary_pattern:
        parts.extend(regex_literals(pattern.primary_pattern.regex))
        parts.extend(pattern.primary_pattern.keywords)
    for secondary in pattern.secondary_patterns:
        parts.extend(regex_literals(secondary.regex))
    seen: set[str] = set()
    unique = []
    for p in parts:
        if p and p.lower() not in seen:
            seen.add(p.lower())
            unique.append(p)
    return " ".join(unique)


# ---------------------------------------------------------------------------
# hashing embedder (no weights, deterministic, lexical)
# ---------------------------------------------------------------------------


class HashingEmbedder:
    """Signed char-n-gram feature hashing into a fixed-dim unit vector.

    Cosine similarity under this embedding measures character-n-gram
    overlap — strong enough to pair "OOMKilled exit code 137" with a
    pattern anchored on "container killed out of memory 137", with zero
    model weights.  Lexical overlap lives at line granularity, so the
    default windows are small (``default_window_lines``); the threshold is
    calibrated against the 12-fixture failure corpus: 0.3 keeps every
    paraphrase recall (tests/test_corpus.py::TestSemanticCalibration) while
    rejecting the strongest observed cross-class overlap (0.2-range hits
    from generic words like "container"/"failed" shared across classes).
    """

    default_threshold = 0.3
    default_window_lines = 4
    default_stride = 2

    #: tokens so common across failure classes (and English) that their
    #: n-grams carry no class signal — every k8s log and every pattern
    #: anchor says "container"/"failed"/"error".  Stripped SYMMETRICALLY
    #: from pattern anchors and log windows before hashing, so similarity
    #: is driven by the distinctive vocabulary (OOMKilled, init, heap,
    #: x509, resolv...).  The neural path embeds the raw text — this list
    #:  is a lexical-embedder concern only.
    GENERIC_TOKENS = frozenset(
        """container containers fail failed failure failures error errors
        pod pods status exit exited code warning restarting restart kubelet
        terminated reason process the a an was were with and for of to in
        is are so not never main after before during""".split()
    )

    def __init__(self, dim: int = 384, ngram_sizes: tuple[int, ...] = (3, 4, 5)) -> None:
        self.dim = dim
        self.ngram_sizes = ngram_sizes

    def _features(self, text: str) -> np.ndarray:
        vec = np.zeros(self.dim, np.float32)
        tokens = [
            t for t in re.split(r"[^a-z0-9]+", text.lower())
            if t and t not in self.GENERIC_TOKENS
        ]
        normalized = " ".join(tokens)
        data = normalized.encode("utf-8", errors="replace")
        for n in self.ngram_sizes:
            if len(data) < n:
                continue
            for i in range(len(data) - n + 1):
                gram = data[i : i + n]
                h = zlib.crc32(gram)
                sign = 1.0 if (h >> 31) & 1 else -1.0
                vec[h % self.dim] += sign
        norm = float(np.linalg.norm(vec))
        if norm > 0:
            vec /= norm
        return vec

    def embed(self, texts: Sequence[str]) -> np.ndarray:
        if not texts:
            return np.zeros((0, self.dim), np.float32)
        return np.stack([self._features(t) for t in texts])


# ---------------------------------------------------------------------------
# neural embedder (JAX encoder, TPU path)
# ---------------------------------------------------------------------------


class NeuralEmbedder:
    """MiniLM-class JAX encoder behind the same embed() interface.

    Batches are padded to fixed (batch, seq) buckets so XLA compiles a
    handful of shapes, not one per request.
    """

    default_threshold = 0.45
    default_window_lines = DEFAULT_WINDOW_LINES
    default_stride = DEFAULT_STRIDE

    def __init__(
        self,
        params,
        config,
        tokenize,  # (text) -> list[int], no specials
        *,
        max_tokens: int = 256,
        batch_size: int = 32,
    ) -> None:
        import threading

        import jax

        from ..models.encoder import encode

        self.params = params
        self.config = config
        self.tokenize = tokenize
        self.max_tokens = min(max_tokens, config.max_positions)
        self.batch_size = batch_size
        self.dim = config.hidden_size
        self._encode = jax.jit(lambda ids, mask: encode(params, config, ids, mask))
        # one instance may be shared by the pipeline's analysis thread and
        # the /v1/embeddings executor; HF fast tokenizers are not safe for
        # concurrent encode on one instance ("Already borrowed")
        self._lock = threading.Lock()

    @classmethod
    def from_checkpoint(
        cls,
        checkpoint_dir: str,
        *,
        max_tokens: int = 256,
        batch_size: int = 32,
    ) -> "NeuralEmbedder":
        """Build from a local sentence-transformers/BERT checkpoint dir
        (safetensors weights + config.json + WordPiece tokenizer files).

        Tokenisation includes the [CLS]/[SEP] specials — the
        sentence-transformers mean-pooling convention counts them, and
        matching it is what makes cosine scores comparable to the public
        MiniLM embeddings.
        """
        from transformers import AutoTokenizer

        from ..models.encoder import load_encoder_params

        params, config = load_encoder_params(checkpoint_dir)
        tok = AutoTokenizer.from_pretrained(checkpoint_dir, local_files_only=True)

        def tokenize(text: str) -> list[int]:
            return tok.encode(text, add_special_tokens=True)

        return cls(
            params, config, tokenize, max_tokens=max_tokens, batch_size=batch_size
        )

    def embed(self, texts: Sequence[str]) -> np.ndarray:
        import numpy as np

        if not texts:
            return np.zeros((0, self.dim), np.float32)
        with self._lock:
            return self._embed_locked(texts)

    def _embed_locked(self, texts: Sequence[str]) -> np.ndarray:
        import numpy as np

        out = []
        for lo in range(0, len(texts), self.batch_size):
            chunk = texts[lo : lo + self.batch_size]
            ids = np.zeros((self.batch_size, self.max_tokens), np.int32)
            mask = np.zeros((self.batch_size, self.max_tokens), np.int32)
            for row, text in enumerate(chunk):
                toks = self.tokenize(text)[: self.max_tokens]
                ids[row, : len(toks)] = toks
                mask[row, : len(toks)] = 1
            emb = np.asarray(self._encode(ids, mask), np.float32)
            out.append(emb[: len(chunk)])
        return np.concatenate(out, axis=0)


def build_embedder(
    encoder_checkpoint_dir: "str | None", *, fallback: bool = True
):
    """The one embedder ladder every surface uses: MiniLM-class neural
    encoder when a checkpoint dir is given and loads, degrading with a
    warning to the lexical ``HashingEmbedder`` (or ``None`` when
    ``fallback=False`` — the semantic matcher treats no-encoder as
    "lexical matching only").

    Call sites: operator/app.py (semantic matcher + embedded completion
    API), serving/__main__.py (standalone API CLI).
    """
    if encoder_checkpoint_dir:
        try:
            embedder = NeuralEmbedder.from_checkpoint(encoder_checkpoint_dir)
            log.info("neural embedder from %s", encoder_checkpoint_dir)
            return embedder
        except Exception:  # noqa: BLE001 - optional neural path degrades
            log.warning(
                "encoder checkpoint %s unusable; degrading to lexical",
                encoder_checkpoint_dir, exc_info=True,
            )
    return HashingEmbedder() if fallback else None


# ---------------------------------------------------------------------------
# the matcher
# ---------------------------------------------------------------------------


class SemanticMatcher:
    """Holds pattern embeddings; scores logs window-by-window.

    ``rebuild(libraries)`` re-embeds all pattern anchor texts (called after
    every pattern sync); ``match(lines)`` embeds the log windows and emits
    an :class:`AnalysisEvent` per pattern whose best window clears the
    similarity threshold.
    """

    def __init__(
        self,
        embedder: Optional[Embedder] = None,
        *,
        threshold: Optional[float] = None,
        window_lines: Optional[int] = None,
        stride: Optional[int] = None,
        max_windows: int = 4096,
    ) -> None:
        self.embedder = embedder or HashingEmbedder()
        self.threshold = (
            threshold
            if threshold is not None
            else getattr(self.embedder, "default_threshold", 0.3)
        )
        # window granularity is an embedder property: lexical overlap lives
        # at line scale, contextual embeddings want wider spans
        self.window_lines = window_lines or getattr(
            self.embedder, "default_window_lines", DEFAULT_WINDOW_LINES
        )
        self.stride = stride or getattr(
            self.embedder, "default_stride", DEFAULT_STRIDE
        )
        self.max_windows = max_windows
        # (patterns, embeddings) swapped as ONE tuple: rebuild() may run in a
        # sync thread while match() runs in an analysis thread; readers take
        # a single snapshot so list and matrix can never be mismatched
        self._state: tuple[list[Pattern], np.ndarray] = (
            [],
            np.zeros((0, self.embedder.dim), np.float32),
        )

    # ------------------------------------------------------------------
    def rebuild(self, libraries: Sequence[LoadedLibrary]) -> int:
        patterns = [p for lib in libraries for p in lib.patterns]
        texts = [embedding_text(p) for p in patterns]
        keep = [i for i, t in enumerate(texts) if t.strip()]
        kept_patterns = [patterns[i] for i in keep]
        embeddings = self.embedder.embed([texts[i] for i in keep])
        self._state = (kept_patterns, embeddings)  # atomic swap
        log.info("semantic matcher: embedded %d patterns", len(kept_patterns))
        return len(kept_patterns)

    @property
    def num_patterns(self) -> int:
        return len(self._state[0])

    # ------------------------------------------------------------------
    def match(self, lines: list[str]) -> list[AnalysisEvent]:
        patterns, pattern_emb = self._state  # one consistent snapshot
        if not lines or not patterns:
            return []
        windows = list(
            iter_windows(lines, window_lines=self.window_lines, stride=self.stride)
        )
        if len(windows) > self.max_windows:
            # evidence concentrates at the tail — keep the newest windows
            windows = windows[-self.max_windows :]
        window_emb = self.embedder.embed([w.text for w in windows])

        scores, best_idx = self._score(window_emb, patterns, pattern_emb)
        events: list[AnalysisEvent] = []
        for i, pattern in enumerate(patterns):
            score = float(scores[i])
            if score < self.threshold:
                continue
            window = windows[int(best_idx[i])]
            events.append(self._to_event(pattern, window, score, lines))
        events.sort(key=lambda e: e.score, reverse=True)
        return events

    def _score(
        self,
        window_emb: np.ndarray,
        patterns: list[Pattern],
        pattern_emb: np.ndarray,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Per-pattern (best score, best window index)."""
        if window_emb.shape[0] == 0:
            n = len(patterns)
            return np.full(n, -1.0, np.float32), np.zeros(n, np.int64)
        try:
            import jax.numpy as jnp

            from ..ops.similarity import best_window_scores

            s, i = best_window_scores(
                jnp.asarray(window_emb), jnp.asarray(pattern_emb)
            )
            return np.asarray(s), np.asarray(i)
        except Exception:  # pragma: no cover - numpy fallback if jax breaks
            log.debug("similarity op unavailable; numpy fallback", exc_info=True)
            matrix = window_emb @ pattern_emb.T
            return matrix.max(axis=0), matrix.argmax(axis=0)

    def _to_event(
        self, pattern: Pattern, window: LogWindow, score: float, lines: list[str]
    ) -> AnalysisEvent:
        # anchor the event at the window's middle line for context display
        line_number = min(window.start + len(window) // 2, len(lines) - 1)
        window_lines = window.text.splitlines()
        mid = min(len(window) // 2, max(len(window_lines) - 1, 0))
        remediation = (
            pattern.remediation.description if pattern.remediation else None
        )
        return AnalysisEvent(
            score=round(score, 4),
            source="semantic",
            matched_pattern=MatchedPattern(
                id=pattern.id,
                name=pattern.name or pattern.id,
                severity=pattern.severity_enum.value,
                category=pattern.category,
                remediation=remediation,
            ),
            context=MatchContext(
                line_number=line_number,
                matched_line=window_lines[mid] if window_lines else "",
                lines_before=window_lines[:mid],
                lines_after=window_lines[mid + 1 :],
            ),
        )
