"""Log windowing.

The reference ships the entire pod log as one string to its parser with no
chunking (reference PodFailureWatcher.java:319-324) and delegates long-log
scaling to the unseen service.  Here windowing is a first-class primitive:
the CPU matcher extracts context windows around hits, and the TPU semantic
path embeds fixed-stride windows so arbitrarily long logs become a dense
``[num_windows, window_tokens]`` batch — the shape the MXU wants
(SURVEY.md §5 long-context entry).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, Optional


@dataclass(frozen=True)
class LogWindow:
    """A contiguous span of log lines. ``start`` is 0-based, ``stop`` exclusive."""

    start: int
    stop: int
    text: str

    def __len__(self) -> int:
        return self.stop - self.start


def split_lines(logs: Optional[str], *, max_lines: int = 100_000) -> list[str]:
    """Split raw pod logs into lines, keeping only the newest ``max_lines``
    (failures live at the tail; an unbounded crash-loop log must not blow up
    memory)."""
    if not logs:
        return []
    lines = logs.splitlines()
    if len(lines) > max_lines:
        lines = lines[-max_lines:]
    return lines


def iter_windows(
    lines: list[str],
    *,
    window_lines: int = 16,
    stride: int = 8,
) -> Iterator[LogWindow]:
    """Fixed-size overlapping windows over the log (stride < window_lines
    gives overlap so a failure signature split across a boundary still lands
    whole in some window)."""
    if not lines:
        return
    if window_lines <= 0 or stride <= 0:
        raise ValueError("window_lines and stride must be positive")
    n = len(lines)
    start = 0
    while True:
        stop = min(start + window_lines, n)
        yield LogWindow(start=start, stop=stop, text="\n".join(lines[start:stop]))
        if stop >= n:
            break
        start += stride


def context_window(
    lines: list[str],
    line_number: int,
    *,
    before: int = 5,
    after: int = 3,
) -> tuple[list[str], list[str]]:
    """Lines surrounding a hit, for MatchContext / prompt construction."""
    lo = max(0, line_number - before)
    hi = min(len(lines), line_number + 1 + after)
    return lines[lo:line_number], lines[line_number + 1 : hi]


def tail_chars(logs: Optional[str], limit: int = 4000) -> str:
    """The newest ``limit`` characters, starting at a line boundary when
    possible — used to cap prompt size."""
    if not logs:
        return ""
    if len(logs) <= limit:
        return logs
    tail = logs[-limit:]
    newline = tail.find("\n")
    if 0 <= newline < len(tail) - 1:
        tail = tail[newline + 1 :]
    return tail
