"""Pattern-match engine — the in-tree replacement for the reference's
external log-parser service (SURVEY.md §2.2, §7 stage 2).

CPU path: regex/keyword scoring (`matcher`).  TPU path: embedding similarity
over pattern anchors (`operator_tpu.patterns.semantic`, added with the
MiniLM encoder)."""

from .engine import PatternEngine, event_evidence_lines, status_evidence_lines
from .loader import (
    LoadedLibrary,
    available_libraries,
    builtin_library_path,
    discover_library_files,
    load_builtin_library,
    load_libraries,
    load_library_file,
)
from .matcher import MatcherConfig, match_libraries, match_pattern, summarize
from .windows import LogWindow, context_window, iter_windows, split_lines, tail_chars

__all__ = [name for name in dir() if not name.startswith("_")]
