"""The storm stack: a full in-process operator→router→serving loop the
open-loop driver can pound.

``build_storm_stack`` assembles the SAME components production wires —
FakeKubeApi, PatternEngine, AnalysisPipeline (with its SLO ledger), a
ProviderRegistry whose ``storm`` backend dispatches through a real
:class:`~..router.core.EngineRouter` over in-process replicas — so a
storm exercises admission, affinity routing, load-feedback shedding,
failover, deadline clamping, and the ledger's journaling together, not a
mocked subset.  Replicas come in two flavours:

- :class:`SyntheticReplica` — deterministic engine-less service times
  with a bounded concurrency gate, so the CPU-only CI smoke shows REAL
  queueing collapse under overload without JAX;
- :class:`EngineReplica` — wraps a live ``ServingEngine`` (bench.py's
  open-loop sweep), mapping SLO class to admission priority and the
  residual budget to a ``SamplingParams.deadline``.

Every storm submit is one ``pipeline.process_pod_failure`` call on a pod
carrying a ``podmortem.io/slo-class`` annotation; the ledger admits at
trace birth and settles in the pipeline's finally, so shed / deadline /
failure outcomes are accounted exactly once per arrival.
"""

from __future__ import annotations

import asyncio
import hashlib
import heapq
from dataclasses import dataclass
from typing import Any, Mapping, Optional

from ..obs import SLOLedger, Tracer, annotate_root, parse_slo_classes
from ..obs.sloledger import SLO_OUTCOME_ATTR
from ..operator.kubeapi import FakeKubeApi
from ..operator.pipeline import AnalysisPipeline
from ..operator.providers import default_registry
from ..patterns.engine import PatternEngine
from ..router import EngineRouter, Replica, RouterError, request_key
from ..router.health import ReplicaLoad
from ..schema.analysis import AIResponse, AnalysisRequest
from ..schema.crds import (
    AIProvider,
    AIProviderRef,
    AIProviderSpec,
    Podmortem,
    PodmortemSpec,
)
from ..schema.kube import (
    ContainerState,
    ContainerStateTerminated,
    ContainerStatus,
    Pod,
    PodStatus,
)
from ..schema.meta import ObjectMeta
from ..utils.config import OperatorConfig
from ..utils.deadline import Deadline
from ..utils.timing import MetricsRegistry

from ..router.value import (
    RECALL_COST_FRACTION,
    OverloadPolicy,
    ShedDecisionLog,
    ValueModel,
)
from .arrivals import ArrivalEvent, ArrivalProcess, ArrivalSpec
from .driver import run_open_loop

__all__ = [
    "EngineReplica",
    "InProcessServingBackend",
    "StormStack",
    "SyntheticReplica",
    "build_storm_stack",
    "run_storm",
]

#: pod annotation the pipeline reads the SLO class from
SLO_CLASS_ANNOTATION = "podmortem.io/slo-class"

#: SLO class -> scheduler admission priority (EDF orders within a class)
CLASS_PRIORITY = {"interactive": 10, "standard": 5, "batch": 0}

#: recall-hot arrivals repeat these EXACT log bodies, so incident-memory
#: fingerprints collide (recall hits) and router affinity keeps them on
#: the replica whose cache is warm
HOT_LOGS = {
    "short": "java.lang.OutOfMemoryError: Java heap space\n"
             "    at com.example.Worker.run(Worker.java:42)\n",
    "long": "java.lang.OutOfMemoryError: Java heap space\n"
            "    at com.example.Batch.process(Batch.java:7)\n"
            + "INFO retrying shard merge\n" * 40,
}


def storm_log(event: ArrivalEvent) -> str:
    """Deterministic log body for one arrival.  Hot events repeat a fixed
    body (fingerprint hit); cold events embed a per-index token so every
    cold failure is a fresh incident class."""
    if event.recall_hot:
        return HOT_LOGS[event.kind]
    # the tag must SURVIVE fingerprint normalization (memory/fingerprint.py
    # folds hex runs to <hex>), so cold events stay distinct incident
    # classes: map the digest onto letters outside [0-9a-f]
    digest = hashlib.sha256(f"cold-{event.index}".encode()).hexdigest()
    tag = "".join(chr(ord("g") + int(c, 16) % 18) for c in digest[:10])
    body = (
        f"java.lang.OutOfMemoryError: Java heap space in stage-{tag}\n"
        f"    at com.example.Cold{tag}.run(Cold.java:{13 + event.index % 80})\n"
    )
    if event.kind == "long":
        body += f"INFO shard {tag} spilling to disk\n" * 40
    return body


def storm_pod(event: ArrivalEvent, *, namespace: str = "storm") -> Pod:
    """A failed pod shaped like the watcher tests' ``failed_pod``, with
    the SLO class riding the annotation the pipeline admits under."""
    return Pod(
        metadata=ObjectMeta(
            name=f"storm-{event.index}",
            namespace=namespace,
            labels={"app": "storm"},
            annotations={SLO_CLASS_ANNOTATION: event.slo_class},
        ),
        status=PodStatus(
            phase="Running",
            container_statuses=[ContainerStatus(
                name="app",
                restart_count=1,
                state=ContainerState(terminated=ContainerStateTerminated(
                    exit_code=137, reason="OOMKilled",
                    finished_at="2026-08-05T00:00:00Z",
                )),
            )],
        ),
    )


# --------------------------------------------------------------------------
# replicas
# --------------------------------------------------------------------------


class SyntheticReplica:
    """An engine-less replica with a REAL concurrency bottleneck.

    Service time is a deterministic function of the request (log volume),
    but at most ``concurrency`` requests are in service at once — excess
    arrivals wait on the gate, so an open-loop storm past capacity shows
    genuine queueing growth (and SLO misses) on a CPU-only box in
    milliseconds, not minutes.  ``time_scale`` compresses service times
    by the same factor the driver compresses arrivals."""

    #: disaggregated service-time split (fabric/disagg.py): the prefill
    #: leg is the prompt-heavy share of one analysis, the decode leg the
    #: rest — a prefill replica serving only prefill legs models the
    #: prompt-bound tier, symmetric for decode
    PHASE_COST = {"full": 1.0, "prefill": 0.6, "decode": 0.4}

    def __init__(
        self,
        replica_id: str,
        *,
        concurrency: int = 4,
        base_ms: float = 5.0,
        per_kb_ms: float = 4.0,
        time_scale: float = 1.0,
        role: str = "mixed",
    ) -> None:
        self.id = replica_id
        self.concurrency = max(1, concurrency)
        self.base_ms = base_ms
        self.per_kb_ms = per_kb_ms
        self.time_scale = time_scale
        self.role = role
        self._gate = asyncio.Semaphore(self.concurrency)
        self.inflight = 0
        self.waiting = 0
        self.served = 0
        #: per-phase serve counts — the disagg smoke's role-honesty gate
        self.served_by_phase: "dict[str, int]" = {}

    def load(self) -> ReplicaLoad:
        return ReplicaLoad(
            queue_depth=self.waiting,
            inflight=self.inflight,
            occupancy=min(1.0, self.inflight / self.concurrency),
            role=self.role,
        )

    def service_ms(self, request: AnalysisRequest) -> float:
        logs = ""
        if request.failure_data is not None:
            logs = request.failure_data.logs or ""
        return self.base_ms + self.per_kb_ms * (len(logs) / 1024.0)

    async def serve(
        self,
        request: AnalysisRequest,
        budget_s: Optional[float],
        degrade_frac: float = 1.0,
        phase: str = "full",
    ) -> AIResponse:
        cost_s = self.service_ms(request) * self.time_scale / 1000.0
        cost_s *= self.PHASE_COST.get(phase, 1.0)
        if degrade_frac < 1.0:
            # overload ladder truncated the analysis depth: a shallower
            # answer costs proportionally less service time
            cost_s *= max(0.05, degrade_frac)
        self.waiting += 1
        try:
            async with self._gate:
                self.waiting -= 1
                self.inflight += 1
                try:
                    await asyncio.sleep(cost_s)
                finally:
                    self.inflight -= 1
        except BaseException:
            # gate wait cancelled (drain) — waiting was already counted
            if self.waiting > 0:
                self.waiting -= 1
            raise
        self.served += 1
        self.served_by_phase[phase] = self.served_by_phase.get(phase, 0) + 1
        fingerprint = request.fingerprint or "cold"
        return AIResponse(
            explanation=(
                f"Root Cause: synthetic analysis of class {fingerprint[:12]}.\n"
                "Fix: inspect the storm harness."
            ),
            provider_id="storm",
            model_id="synthetic",
            completion_tokens=24,
            deadline_outcome="completed" if budget_s is not None else None,
        )


class EngineReplica:
    """A live ``ServingEngine`` behind the storm router (bench.py's
    open-loop sweep uses one per engine).  Imports serving lazily so the
    loadgen package stays importable on JAX-less boxes."""

    def __init__(self, replica_id: str, engine: Any, *, max_tokens: int = 48) -> None:
        self.id = replica_id
        self.engine = engine
        self.max_tokens = max_tokens

    def load(self) -> ReplicaLoad:
        return self.engine.load_report()

    async def serve(
        self,
        request: AnalysisRequest,
        budget_s: Optional[float],
        degrade_frac: float = 1.0,
        phase: str = "full",
    ) -> AIResponse:
        from ..serving.types import SamplingParams

        logs = ""
        slo_class = getattr(request, "slo_class", None)
        if request.failure_data is not None:
            logs = request.failure_data.logs or ""
            slo_class = slo_class or getattr(
                request.failure_data, "slo_class", None
            )
        prompt = f"Explain this pod failure:\n{logs[:2048]}\nRoot cause:"
        deadline = (
            self.engine.generator._clock() + budget_s
            if budget_s is not None
            else None
        )
        max_tokens = self.max_tokens
        if phase == "prefill":
            # disaggregated prefill leg: run the full prompt for exactly
            # one token — the decode leg picks up over the fabric
            max_tokens = 1
        if degrade_frac < 1.0:
            max_tokens = max(1, int(max_tokens * degrade_frac))
        params = SamplingParams(
            max_tokens=max_tokens,
            temperature=0.0,
            deadline=deadline,
            slo_class=slo_class,
            degraded=degrade_frac < 1.0,
            recall_p=getattr(request, "recall_p", 0.0),
        )
        priority = CLASS_PRIORITY.get(slo_class or "", 5)
        result = await self.engine.generate(prompt, params, priority=priority)
        return AIResponse(
            explanation=result.text,
            provider_id="storm",
            model_id="tpu-native",
            completion_tokens=result.completion_tokens,
            deadline_outcome=(
                "deadline-exceeded" if result.finish_reason == "deadline"
                and not result.completion_tokens else
                "truncated" if result.finish_reason == "deadline"
                else "degraded" if result.finish_reason == "degraded"
                else "completed" if budget_s is not None else None
            ),
        )


# --------------------------------------------------------------------------
# the routed backend
# --------------------------------------------------------------------------


class InProcessServingBackend:
    """AIProviderBackend dispatching through a real EngineRouter over
    in-process replicas — the storm's serving plane.

    The dispatch mirrors ``OpenAICompatProvider.generate`` (affinity from
    fingerprint/prefix, absolute deadline envelope, failover across the
    set) but ``send`` is a direct coroutine call instead of HTTP, and
    load feedback comes straight from the replicas' own reports before
    every route, so shedding reacts to THIS storm's queue depths."""

    def __init__(
        self,
        replicas: "list[SyntheticReplica | EngineReplica]",
        *,
        metrics: Optional[MetricsRegistry] = None,
        shed_pressure: int = 8,
        max_failover: int = 1,
        allow_empty: bool = False,
        disaggregate: bool = False,
    ) -> None:
        if not replicas and not allow_empty:
            raise ValueError("storm backend needs at least one replica")
        self.replicas = {r.id: r for r in replicas}
        self.metrics = metrics
        #: fabric disaggregation (fabric/disagg.py): every analysis runs
        #: as a prefill leg + a decode leg, role-preferred routing each
        self.disaggregate = disaggregate
        self.router = EngineRouter(
            [Replica(id=r.id, url=f"inproc://{r.id}") for r in replicas],
            shed_pressure=shed_pressure,
            max_failover=max_failover,
            metrics=metrics,
        )
        #: pulsed on every membership change; arrivals against an empty
        #: fleet wait here for the autoscaler to wake a replica
        self._members_changed = asyncio.Event()

    # -- elastic membership (docs/SCALING.md): the discovery loop mutates
    # the serving plane mid-storm through these, without restart --------
    def add_replica(
        self, replica: "SyntheticReplica | EngineReplica"
    ) -> None:
        self.replicas[replica.id] = replica
        self.router.add(Replica(id=replica.id, url=f"inproc://{replica.id}"))
        self._members_changed.set()

    def remove_replica(self, replica_id: str) -> None:
        self.replicas.pop(replica_id, None)
        self.router.remove(replica_id)
        self._members_changed.set()

    def _feed_load(self) -> None:
        for rid, replica in self.replicas.items():
            try:
                self.router.report_load(rid, replica.load())
            except Exception:  # a torn load report must not kill dispatch
                continue

    async def generate(self, request: AnalysisRequest) -> AIResponse:
        logs = ""
        if request.failure_data is not None:
            logs = request.failure_data.logs or ""
        prompt_basis = logs[:512] or "empty"
        budget = (
            Deadline.start(request.deadline_s)
            if request.deadline_s is not None
            else None
        )
        # scale-from-zero: an arrival against an EMPTY fleet is the wake
        # signal (the autoscaler sees it as ledger pending) — wait for a
        # member to join instead of failing, bounded by the arrival's own
        # deadline envelope so a fleet that never wakes settles as a
        # deadline miss, not a hang
        while len(self.router) == 0:
            self._members_changed.clear()
            if len(self.router):
                break  # joined between the check and the clear
            wait_s = budget.remaining() if budget is not None else 5.0
            if wait_s <= 0.0:
                return AIResponse(
                    error="deadline exhausted waiting for the fleet to "
                          "wake from zero",
                    provider_id="storm",
                    deadline_outcome="deadline-exceeded",
                )
            try:
                await asyncio.wait_for(
                    self._members_changed.wait(), timeout=min(wait_s, 5.0)
                )
            except asyncio.TimeoutError:
                continue
        self._feed_load()

        # value-aware overload ladder (router/value.py): consult BEFORE
        # dispatch so a storm past the collapse point degrades low-value
        # work (shallower analysis) and sheds only the lowest-value tail,
        # never the protected class — the router's raw pressure shed stays
        # as the backstop underneath
        degrade_frac = 1.0
        if getattr(self.router, "policy", None) is not None:
            verdict = self.router.overload_verdict(
                value=self.router.policy.model.value(
                    slo_class=getattr(request, "slo_class", None),
                    residual_s=budget.remaining() if budget is not None else None,
                    recall_p=getattr(request, "recall_p", 0.0),
                ),
                request_id=request_key(prompt_basis),
                site="storm",
            )
            if verdict is not None and verdict.action == "shed":
                annotate_root(SLO_OUTCOME_ATTR, "shed", overwrite=False)
                return AIResponse(
                    error="shed by overload ladder (lowest value at storm "
                          "admission)",
                    provider_id="storm",
                    deadline_outcome="shed",
                )
            if verdict is not None and verdict.action == "degrade":
                degrade_frac = verdict.degrade_tokens_frac

        async def send(
            replica: Replica, attempt: int, budget_s: Optional[float]
        ) -> AIResponse:
            target = self.replicas[replica.id]
            return await target.serve(request, budget_s, degrade_frac)

        key = EngineRouter.affinity_key(
            prefix=prompt_basis, fingerprint=request.fingerprint
        )
        rid = request_key(prompt_basis)
        try:
            if self.disaggregate:
                from ..fabric.disagg import disaggregated_dispatch

                async def prefill_send(replica, attempt, budget_s):
                    target = self.replicas[replica.id]
                    return await target.serve(
                        request, budget_s, degrade_frac, phase="prefill"
                    )

                async def decode_send(replica, attempt, budget_s, prefix):
                    target = self.replicas[replica.id]
                    return await target.serve(
                        request, budget_s, degrade_frac, phase="decode"
                    )

                _prefill, outcome = await disaggregated_dispatch(
                    self.router, prefill_send, decode_send,
                    key=key, request_id=rid, deadline=budget,
                    metrics=self.metrics,
                )
            else:
                outcome = await self.router.dispatch(
                    send,
                    key=key,
                    request_id=rid,
                    deadline=budget,
                    attempts=1,
                )
        except RouterError as exc:
            deadline_spent = budget is not None and budget.remaining() <= 0.0
            if not deadline_spent:
                # load-refused: the ledger settles this arrival as shed,
                # not failed (the root-span override sloledger reads)
                annotate_root(SLO_OUTCOME_ATTR, "shed", overwrite=False)
            return AIResponse(
                error=f"storm dispatch failed: {exc}",
                provider_id="storm",
                deadline_outcome="deadline-exceeded" if deadline_spent else None,
                replica_id=exc.tried[-1] if exc.tried else None,
            )
        response: AIResponse = outcome.response
        response.replica_id = outcome.replica_id
        response.requeues = outcome.requeues
        if (
            degrade_frac < 1.0
            and response.explanation
            and not response.error
            and response.deadline_outcome in (None, "completed")
        ):
            # the ladder shortened this analysis and it still landed —
            # a DISTINCT terminal outcome, not a deadline miss
            response.deadline_outcome = "degraded"
        return response

    def fleet_view(self) -> dict:
        self._feed_load()
        view = self.router.health.fleet_view()
        # the autoscaler's burst signal (least-loaded healthy pressure)
        view["fleet"]["pressure"] = self.router.fleet_pressure()
        return view


# --------------------------------------------------------------------------
# stack assembly + the storm loop
# --------------------------------------------------------------------------


@dataclass
class StormStack:
    """Everything one storm drives, pre-wired.  ``submit`` is the
    open-loop driver's callable: one arrival -> one full analysis."""

    api: FakeKubeApi
    config: OperatorConfig
    metrics: MetricsRegistry
    pipeline: AnalysisPipeline
    ledger: SLOLedger
    backend: InProcessServingBackend
    podmortem: Podmortem
    namespace: str = "storm"
    deadline_factor: float = 4.0
    time_scale: float = 1.0

    async def submit(self, event: ArrivalEvent) -> None:
        pod = storm_pod(event, namespace=self.namespace)
        self.api.set_pod_log(self.namespace, pod.metadata.name,
                             storm_log(event))
        target_s = self.ledger.classes.get(
            event.slo_class,
            self.ledger.classes[self.ledger.default_class],
        )
        envelope_s = max(0.25, target_s * self.deadline_factor * self.time_scale)
        await self.pipeline.process_pod_failure(
            pod, self.podmortem,
            failure_time=f"storm-t{event.index}",
            deadline=Deadline.start(envelope_s),
        )

    def close(self) -> None:
        self.ledger.close()


async def build_storm_stack(
    *,
    replicas: "Optional[list[SyntheticReplica | EngineReplica]]" = None,
    config: Optional[OperatorConfig] = None,
    metrics: Optional[MetricsRegistry] = None,
    ledger_path: Optional[str] = None,
    time_scale: float = 1.0,
    deadline_factor: float = 4.0,
    namespace: str = "storm",
    fault_plan: Any = None,
    disaggregate: bool = False,
) -> StormStack:
    """Wire the full storm stack.  Defaults give the CI smoke shape: two
    synthetic replicas, in-memory pattern cache, ledger journaled to
    ``ledger_path`` when set."""
    api = FakeKubeApi()
    if fault_plan is not None:
        api.fault_plan = fault_plan
    config = config or OperatorConfig(
        pattern_cache_directory="/nonexistent",
        conflict_backoff_base_s=0.001,
        memory_enabled=True,
    )
    metrics = metrics or MetricsRegistry()
    ledger = SLOLedger(
        parse_slo_classes(config.slo_classes),
        path=ledger_path,
        metrics=metrics,
    )
    # an EXPLICIT empty list is the elastic (scale-from-zero) shape: the
    # fleet starts at zero and membership arrives through add_replica;
    # None keeps the classic two-synthetic-replica CI smoke
    allow_empty = replicas is not None and not replicas
    if replicas is None:
        replicas = [
            SyntheticReplica(f"storm-replica-{i}", time_scale=time_scale)
            for i in range(2)
        ]
    backend = InProcessServingBackend(
        replicas, metrics=metrics, allow_empty=allow_empty,
        disaggregate=disaggregate,
    )
    if fault_plan is not None:
        # the router's dispatch seam joins the same plan as the apiserver
        # (router.dispatch — replica kills/partitions in the data plane)
        backend.router.fault_plan = fault_plan
    registry = default_registry()
    registry.register("storm", backend)
    pipeline = AnalysisPipeline(
        api, PatternEngine(), config=config, metrics=metrics,
        providers=registry, tracer=Tracer(recorder=None),
        slo_ledger=ledger,
    )
    # one value model for the whole chain: the storm backend's router
    # consults the SAME policy (same attainment feed, same decision log)
    # the pipeline built, so shed/degrade ordering is provable end-to-end
    backend.router.policy = pipeline.overload_policy
    provider = AIProvider(
        metadata=ObjectMeta(name="storm", namespace=namespace),
        spec=AIProviderSpec(provider_id="storm", model_id="storm"),
    )
    await api.create("AIProvider", provider.to_dict())
    podmortem = Podmortem(
        metadata=ObjectMeta(name="storm", namespace=namespace),
        spec=PodmortemSpec(
            ai_provider_ref=AIProviderRef(name="storm", namespace=namespace),
        ),
    )
    await api.create("Podmortem", podmortem.to_dict())
    return StormStack(
        api=api, config=config, metrics=metrics, pipeline=pipeline,
        ledger=ledger, backend=backend, podmortem=podmortem,
        namespace=namespace, deadline_factor=deadline_factor,
        time_scale=time_scale,
    )


async def run_storm(
    stack: StormStack,
    process: ArrivalProcess,
    *,
    drain_s: float = 30.0,
) -> dict:
    """Drive one storm open-loop and fold the ledger's verdict into the
    driver's offered/achieved accounting — the record bench.py publishes
    as ``open_loop`` and the CI smoke asserts on."""
    report = await run_open_loop(
        stack.submit, process,
        time_scale=stack.time_scale, drain_s=drain_s,
    )
    snapshot = stack.ledger.snapshot()
    return {
        "arrival_spec": process.spec.to_dict(),
        "seed": process.seed,
        "fingerprint": process.fingerprint(),
        **report,
        "slo": snapshot,
        "fleet": stack.backend.fleet_view(),
        "overload": _overload_evidence(stack),
    }


def _overload_evidence(stack: StormStack) -> Optional[dict]:
    """The overload ladder's verdict for one storm: labeled shed/degrade
    totals, per-class splits, and a digest of the decision log (two runs
    of the same seeded storm against a deterministic pressure trace must
    produce byte-identical logs — tests/test_value.py proves the policy
    layer; the digest makes a live storm's log comparable at a glance)."""
    policy = getattr(stack.pipeline, "overload_policy", None)
    if policy is None:
        return None

    def by_class(name: str) -> "dict[str, int]":
        out: dict[str, int] = {}
        for key, count in stack.metrics.labeled(name).items():
            cls = dict(key).get("slo_class", "unknown")
            out[cls] = out.get(cls, 0) + count
        return out

    log_text = policy.log.text()
    return {
        "shed_total": stack.metrics.labeled_total("shed"),
        "degraded_total": stack.metrics.labeled_total("degraded"),
        "shed_by_class": by_class("shed"),
        "degraded_by_class": by_class("degraded"),
        "attainment_by_class": stack.ledger.attainment_by_class(),
        "decisions": len(policy.log.lines()),
        "decisions_dropped": policy.log.dropped,
        "decision_log_sha256":
            hashlib.sha256(log_text.encode("utf-8")).hexdigest(),
    }


def simulate_overload(
    rate_per_min: float,
    *,
    seed: int = 0,
    duration_s: float = 60.0,
    servers: int = 4,
    service_s: float = 0.35,
    long_service_s: float = 0.9,
    classes: Optional[Mapping[str, float]] = None,
    shed_pressure: float = 8.0,
    degrade_pressure: Optional[float] = None,
    degrade_tokens_frac: float = 0.25,
    shed_value_floor: float = 1.0,
    attainment_target: float = 0.9,
) -> dict:
    """One overload storm replayed through the production value ladder in
    VIRTUAL time — the deterministic proof surface for the 2×-collapse CI
    pass.

    The live ladder keys off measured queue pressure, which is a
    contention signal BY DESIGN: wall-clock attainment of a 2-second
    interactive target on a loaded CI runner says more about the runner
    than the ladder, so a live-stack gate flakes in both directions (an
    idle host never overloads; a contended one cliffs).  Here the same
    seeded :class:`ArrivalProcess` schedule is replayed against an M/D/c
    queue with a virtual clock — ``servers`` slots, deterministic
    per-kind service times, recall hits at ~:data:`RECALL_COST_FRACTION
    <..router.value.RECALL_COST_FRACTION>` of cold cost, degraded work
    shortened to ``degrade_tokens_frac`` — and every arrival is decided
    by the SAME :class:`~..router.value.OverloadPolicy` /
    :class:`~..router.value.ValueModel` the pipeline wires, with
    pressure = unfinished jobs at the arrival instant.  The per-class
    attainment feeding class protection updates CAUSALLY (only jobs
    finished strictly before the deciding arrival count), so the
    protect-below-target loop closes exactly as it does live.

    No wall clock, no ambient randomness (GL007): the same ``(seed,
    rate, knobs)`` returns a byte-identical decision log and result row.
    """
    class_targets = dict(
        classes if classes is not None
        else {"interactive": 2.0, "standard": 30.0, "batch": 120.0}
    )
    events = ArrivalProcess(
        ArrivalSpec(
            name="poisson", rate_per_min=rate_per_min,
            duration_s=duration_s,
        ),
        seed=seed,
    ).materialize()
    counts = {
        c: {"admitted": 0, "attained": 0, "missed": 0,
            "shed": 0, "degraded": 0}
        for c in class_targets
    }

    def attainment() -> "dict[str, Optional[float]]":
        out: "dict[str, Optional[float]]" = {}
        for cls, k in counts.items():
            settled = k["attained"] + k["missed"]
            out[cls] = (k["attained"] / settled) if settled else None
        return out

    model = ValueModel(
        class_targets, attainment=attainment,
        attainment_target=attainment_target,
    )
    policy = OverloadPolicy(
        model,
        shed_pressure=shed_pressure,
        degrade_pressure=degrade_pressure,
        degrade_tokens_frac=degrade_tokens_frac,
        shed_value_floor=shed_value_floor,
        log=ShedDecisionLog(cap=65536),
    )
    free = [0.0] * max(1, int(servers))  # per-slot next-free virtual time
    heapq.heapify(free)
    # (finish_time, slo_class, attained) for every unfinished admitted job;
    # its length at an arrival IS the pressure signal (queued + inflight)
    settle: "list[tuple[float, str, bool]]" = []
    protected_shed = 0
    for event in events:
        # settle jobs that finished before this arrival FIRST so the
        # attainment feed (and therefore protection) stays causal
        while settle and settle[0][0] <= event.at_s:
            _, cls, ok = heapq.heappop(settle)
            counts[cls]["attained" if ok else "missed"] += 1
        cls = event.slo_class
        counts.setdefault(
            cls, {"admitted": 0, "attained": 0, "missed": 0,
                  "shed": 0, "degraded": 0},
        )
        counts[cls]["admitted"] += 1
        pressure = float(len(settle))
        value = model.value(
            slo_class=cls,
            recall_p=1.0 if event.recall_hot else 0.0,
        )
        verdict = policy.decide(
            value, pressure, site="sim", request_id=f"req-{event.index}",
        )
        if verdict.action == "shed":
            counts[cls]["missed"] += 1
            counts[cls]["shed"] += 1
            if value.protected:
                protected_shed += 1
            continue
        cost = long_service_s if event.kind == "long" else service_s
        if event.recall_hot:
            cost *= RECALL_COST_FRACTION
        if verdict.action == "degrade":
            counts[cls]["degraded"] += 1
            cost *= max(0.05, verdict.degrade_tokens_frac)
        start = max(event.at_s, heapq.heappop(free))
        finish = start + cost
        heapq.heappush(free, finish)
        # a degraded completion inside its target still ATTAINS — that is
        # the degrade-before-reject mechanism paying out (the live
        # sloledger applies the same rule to "degraded" outcomes)
        target = class_targets.get(cls, 0.0)
        heapq.heappush(settle, (finish, cls, finish - event.at_s <= target))
    while settle:
        _, cls, ok = heapq.heappop(settle)
        counts[cls]["attained" if ok else "missed"] += 1

    att = attainment()
    settled_total = sum(k["attained"] + k["missed"] for k in counts.values())
    attained_total = sum(k["attained"] for k in counts.values())
    log_text = policy.log.text()
    return {
        "rate_per_min": float(rate_per_min),
        "arrivals": len(events),
        "attainment": (
            attained_total / settled_total if settled_total else None
        ),
        "attainment_by_class": att,
        "shed_total": sum(k["shed"] for k in counts.values()),
        "degraded_total": sum(k["degraded"] for k in counts.values()),
        "shed_by_class": {
            c: k["shed"] for c, k in counts.items() if k["shed"]
        },
        "degraded_by_class": {
            c: k["degraded"] for c, k in counts.items() if k["degraded"]
        },
        "protected_shed": protected_shed,
        "protected": sorted(model.protected_classes()),
        "decisions": len(policy.log.lines()),
        "decisions_dropped": policy.log.dropped,
        "decision_log": log_text,
        "decision_log_sha256":
            hashlib.sha256(log_text.encode("utf-8")).hexdigest(),
    }
