"""The storm stack: a full in-process operator→router→serving loop the
open-loop driver can pound.

``build_storm_stack`` assembles the SAME components production wires —
FakeKubeApi, PatternEngine, AnalysisPipeline (with its SLO ledger), a
ProviderRegistry whose ``storm`` backend dispatches through a real
:class:`~..router.core.EngineRouter` over in-process replicas — so a
storm exercises admission, affinity routing, load-feedback shedding,
failover, deadline clamping, and the ledger's journaling together, not a
mocked subset.  Replicas come in two flavours:

- :class:`SyntheticReplica` — deterministic engine-less service times
  with a bounded concurrency gate, so the CPU-only CI smoke shows REAL
  queueing collapse under overload without JAX;
- :class:`EngineReplica` — wraps a live ``ServingEngine`` (bench.py's
  open-loop sweep), mapping SLO class to admission priority and the
  residual budget to a ``SamplingParams.deadline``.

Every storm submit is one ``pipeline.process_pod_failure`` call on a pod
carrying a ``podmortem.io/slo-class`` annotation; the ledger admits at
trace birth and settles in the pipeline's finally, so shed / deadline /
failure outcomes are accounted exactly once per arrival.
"""

from __future__ import annotations

import asyncio
import hashlib
from dataclasses import dataclass
from typing import Any, Optional

from ..obs import SLOLedger, Tracer, annotate_root, parse_slo_classes
from ..obs.sloledger import SLO_OUTCOME_ATTR
from ..operator.kubeapi import FakeKubeApi
from ..operator.pipeline import AnalysisPipeline
from ..operator.providers import default_registry
from ..patterns.engine import PatternEngine
from ..router import EngineRouter, Replica, RouterError, request_key
from ..router.health import ReplicaLoad
from ..schema.analysis import AIResponse, AnalysisRequest
from ..schema.crds import (
    AIProvider,
    AIProviderRef,
    AIProviderSpec,
    Podmortem,
    PodmortemSpec,
)
from ..schema.kube import (
    ContainerState,
    ContainerStateTerminated,
    ContainerStatus,
    Pod,
    PodStatus,
)
from ..schema.meta import ObjectMeta
from ..utils.config import OperatorConfig
from ..utils.deadline import Deadline
from ..utils.timing import MetricsRegistry

from .arrivals import ArrivalEvent, ArrivalProcess
from .driver import run_open_loop

__all__ = [
    "EngineReplica",
    "InProcessServingBackend",
    "StormStack",
    "SyntheticReplica",
    "build_storm_stack",
    "run_storm",
]

#: pod annotation the pipeline reads the SLO class from
SLO_CLASS_ANNOTATION = "podmortem.io/slo-class"

#: SLO class -> scheduler admission priority (EDF orders within a class)
CLASS_PRIORITY = {"interactive": 10, "standard": 5, "batch": 0}

#: recall-hot arrivals repeat these EXACT log bodies, so incident-memory
#: fingerprints collide (recall hits) and router affinity keeps them on
#: the replica whose cache is warm
HOT_LOGS = {
    "short": "java.lang.OutOfMemoryError: Java heap space\n"
             "    at com.example.Worker.run(Worker.java:42)\n",
    "long": "java.lang.OutOfMemoryError: Java heap space\n"
            "    at com.example.Batch.process(Batch.java:7)\n"
            + "INFO retrying shard merge\n" * 40,
}


def storm_log(event: ArrivalEvent) -> str:
    """Deterministic log body for one arrival.  Hot events repeat a fixed
    body (fingerprint hit); cold events embed a per-index token so every
    cold failure is a fresh incident class."""
    if event.recall_hot:
        return HOT_LOGS[event.kind]
    # the tag must SURVIVE fingerprint normalization (memory/fingerprint.py
    # folds hex runs to <hex>), so cold events stay distinct incident
    # classes: map the digest onto letters outside [0-9a-f]
    digest = hashlib.sha256(f"cold-{event.index}".encode()).hexdigest()
    tag = "".join(chr(ord("g") + int(c, 16) % 18) for c in digest[:10])
    body = (
        f"java.lang.OutOfMemoryError: Java heap space in stage-{tag}\n"
        f"    at com.example.Cold{tag}.run(Cold.java:{13 + event.index % 80})\n"
    )
    if event.kind == "long":
        body += f"INFO shard {tag} spilling to disk\n" * 40
    return body


def storm_pod(event: ArrivalEvent, *, namespace: str = "storm") -> Pod:
    """A failed pod shaped like the watcher tests' ``failed_pod``, with
    the SLO class riding the annotation the pipeline admits under."""
    return Pod(
        metadata=ObjectMeta(
            name=f"storm-{event.index}",
            namespace=namespace,
            labels={"app": "storm"},
            annotations={SLO_CLASS_ANNOTATION: event.slo_class},
        ),
        status=PodStatus(
            phase="Running",
            container_statuses=[ContainerStatus(
                name="app",
                restart_count=1,
                state=ContainerState(terminated=ContainerStateTerminated(
                    exit_code=137, reason="OOMKilled",
                    finished_at="2026-08-05T00:00:00Z",
                )),
            )],
        ),
    )


# --------------------------------------------------------------------------
# replicas
# --------------------------------------------------------------------------


class SyntheticReplica:
    """An engine-less replica with a REAL concurrency bottleneck.

    Service time is a deterministic function of the request (log volume),
    but at most ``concurrency`` requests are in service at once — excess
    arrivals wait on the gate, so an open-loop storm past capacity shows
    genuine queueing growth (and SLO misses) on a CPU-only box in
    milliseconds, not minutes.  ``time_scale`` compresses service times
    by the same factor the driver compresses arrivals."""

    def __init__(
        self,
        replica_id: str,
        *,
        concurrency: int = 4,
        base_ms: float = 5.0,
        per_kb_ms: float = 4.0,
        time_scale: float = 1.0,
    ) -> None:
        self.id = replica_id
        self.concurrency = max(1, concurrency)
        self.base_ms = base_ms
        self.per_kb_ms = per_kb_ms
        self.time_scale = time_scale
        self._gate = asyncio.Semaphore(self.concurrency)
        self.inflight = 0
        self.waiting = 0
        self.served = 0

    def load(self) -> ReplicaLoad:
        return ReplicaLoad(
            queue_depth=self.waiting,
            inflight=self.inflight,
            occupancy=min(1.0, self.inflight / self.concurrency),
        )

    def service_ms(self, request: AnalysisRequest) -> float:
        logs = ""
        if request.failure_data is not None:
            logs = request.failure_data.logs or ""
        return self.base_ms + self.per_kb_ms * (len(logs) / 1024.0)

    async def serve(
        self, request: AnalysisRequest, budget_s: Optional[float]
    ) -> AIResponse:
        cost_s = self.service_ms(request) * self.time_scale / 1000.0
        self.waiting += 1
        try:
            async with self._gate:
                self.waiting -= 1
                self.inflight += 1
                try:
                    await asyncio.sleep(cost_s)
                finally:
                    self.inflight -= 1
        except BaseException:
            # gate wait cancelled (drain) — waiting was already counted
            if self.waiting > 0:
                self.waiting -= 1
            raise
        self.served += 1
        fingerprint = request.fingerprint or "cold"
        return AIResponse(
            explanation=(
                f"Root Cause: synthetic analysis of class {fingerprint[:12]}.\n"
                "Fix: inspect the storm harness."
            ),
            provider_id="storm",
            model_id="synthetic",
            completion_tokens=24,
            deadline_outcome="completed" if budget_s is not None else None,
        )


class EngineReplica:
    """A live ``ServingEngine`` behind the storm router (bench.py's
    open-loop sweep uses one per engine).  Imports serving lazily so the
    loadgen package stays importable on JAX-less boxes."""

    def __init__(self, replica_id: str, engine: Any, *, max_tokens: int = 48) -> None:
        self.id = replica_id
        self.engine = engine
        self.max_tokens = max_tokens

    def load(self) -> ReplicaLoad:
        return self.engine.load_report()

    async def serve(
        self, request: AnalysisRequest, budget_s: Optional[float]
    ) -> AIResponse:
        from ..serving.types import SamplingParams

        logs = ""
        slo_class = None
        if request.failure_data is not None:
            logs = request.failure_data.logs or ""
            slo_class = getattr(request.failure_data, "slo_class", None)
        prompt = f"Explain this pod failure:\n{logs[:2048]}\nRoot cause:"
        deadline = (
            self.engine.generator._clock() + budget_s
            if budget_s is not None
            else None
        )
        params = SamplingParams(
            max_tokens=self.max_tokens,
            temperature=0.0,
            deadline=deadline,
            slo_class=slo_class,
        )
        priority = CLASS_PRIORITY.get(slo_class or "", 5)
        result = await self.engine.generate(prompt, params, priority=priority)
        return AIResponse(
            explanation=result.text,
            provider_id="storm",
            model_id="tpu-native",
            completion_tokens=result.completion_tokens,
            deadline_outcome=(
                "deadline-exceeded" if result.finish_reason == "deadline"
                and not result.completion_tokens else
                "truncated" if result.finish_reason == "deadline"
                else "completed" if budget_s is not None else None
            ),
        )


# --------------------------------------------------------------------------
# the routed backend
# --------------------------------------------------------------------------


class InProcessServingBackend:
    """AIProviderBackend dispatching through a real EngineRouter over
    in-process replicas — the storm's serving plane.

    The dispatch mirrors ``OpenAICompatProvider.generate`` (affinity from
    fingerprint/prefix, absolute deadline envelope, failover across the
    set) but ``send`` is a direct coroutine call instead of HTTP, and
    load feedback comes straight from the replicas' own reports before
    every route, so shedding reacts to THIS storm's queue depths."""

    def __init__(
        self,
        replicas: "list[SyntheticReplica | EngineReplica]",
        *,
        metrics: Optional[MetricsRegistry] = None,
        shed_pressure: int = 8,
        max_failover: int = 1,
    ) -> None:
        if not replicas:
            raise ValueError("storm backend needs at least one replica")
        self.replicas = {r.id: r for r in replicas}
        self.metrics = metrics
        self.router = EngineRouter(
            [Replica(id=r.id, url=f"inproc://{r.id}") for r in replicas],
            shed_pressure=shed_pressure,
            max_failover=max_failover,
            metrics=metrics,
        )

    def _feed_load(self) -> None:
        for rid, replica in self.replicas.items():
            try:
                self.router.report_load(rid, replica.load())
            except Exception:  # a torn load report must not kill dispatch
                continue

    async def generate(self, request: AnalysisRequest) -> AIResponse:
        logs = ""
        if request.failure_data is not None:
            logs = request.failure_data.logs or ""
        prompt_basis = logs[:512] or "empty"
        budget = (
            Deadline.start(request.deadline_s)
            if request.deadline_s is not None
            else None
        )
        self._feed_load()

        async def send(
            replica: Replica, attempt: int, budget_s: Optional[float]
        ) -> AIResponse:
            target = self.replicas[replica.id]
            return await target.serve(request, budget_s)

        try:
            outcome = await self.router.dispatch(
                send,
                key=EngineRouter.affinity_key(
                    prefix=prompt_basis, fingerprint=request.fingerprint
                ),
                request_id=request_key(prompt_basis),
                deadline=budget,
                attempts=1,
            )
        except RouterError as exc:
            deadline_spent = budget is not None and budget.remaining() <= 0.0
            if not deadline_spent:
                # load-refused: the ledger settles this arrival as shed,
                # not failed (the root-span override sloledger reads)
                annotate_root(SLO_OUTCOME_ATTR, "shed", overwrite=False)
            return AIResponse(
                error=f"storm dispatch failed: {exc}",
                provider_id="storm",
                deadline_outcome="deadline-exceeded" if deadline_spent else None,
                replica_id=exc.tried[-1] if exc.tried else None,
            )
        response: AIResponse = outcome.response
        response.replica_id = outcome.replica_id
        response.requeues = outcome.requeues
        return response

    def fleet_view(self) -> dict:
        self._feed_load()
        return self.router.health.fleet_view()


# --------------------------------------------------------------------------
# stack assembly + the storm loop
# --------------------------------------------------------------------------


@dataclass
class StormStack:
    """Everything one storm drives, pre-wired.  ``submit`` is the
    open-loop driver's callable: one arrival -> one full analysis."""

    api: FakeKubeApi
    config: OperatorConfig
    metrics: MetricsRegistry
    pipeline: AnalysisPipeline
    ledger: SLOLedger
    backend: InProcessServingBackend
    podmortem: Podmortem
    namespace: str = "storm"
    deadline_factor: float = 4.0
    time_scale: float = 1.0

    async def submit(self, event: ArrivalEvent) -> None:
        pod = storm_pod(event, namespace=self.namespace)
        self.api.set_pod_log(self.namespace, pod.metadata.name,
                             storm_log(event))
        target_s = self.ledger.classes.get(
            event.slo_class,
            self.ledger.classes[self.ledger.default_class],
        )
        envelope_s = max(0.25, target_s * self.deadline_factor * self.time_scale)
        await self.pipeline.process_pod_failure(
            pod, self.podmortem,
            failure_time=f"storm-t{event.index}",
            deadline=Deadline.start(envelope_s),
        )

    def close(self) -> None:
        self.ledger.close()


async def build_storm_stack(
    *,
    replicas: "Optional[list[SyntheticReplica | EngineReplica]]" = None,
    config: Optional[OperatorConfig] = None,
    metrics: Optional[MetricsRegistry] = None,
    ledger_path: Optional[str] = None,
    time_scale: float = 1.0,
    deadline_factor: float = 4.0,
    namespace: str = "storm",
    fault_plan: Any = None,
) -> StormStack:
    """Wire the full storm stack.  Defaults give the CI smoke shape: two
    synthetic replicas, in-memory pattern cache, ledger journaled to
    ``ledger_path`` when set."""
    api = FakeKubeApi()
    if fault_plan is not None:
        api.fault_plan = fault_plan
    config = config or OperatorConfig(
        pattern_cache_directory="/nonexistent",
        conflict_backoff_base_s=0.001,
        memory_enabled=True,
    )
    metrics = metrics or MetricsRegistry()
    ledger = SLOLedger(
        parse_slo_classes(config.slo_classes),
        path=ledger_path,
        metrics=metrics,
    )
    if replicas is None:
        replicas = [
            SyntheticReplica(f"storm-replica-{i}", time_scale=time_scale)
            for i in range(2)
        ]
    backend = InProcessServingBackend(replicas, metrics=metrics)
    registry = default_registry()
    registry.register("storm", backend)
    pipeline = AnalysisPipeline(
        api, PatternEngine(), config=config, metrics=metrics,
        providers=registry, tracer=Tracer(recorder=None),
        slo_ledger=ledger,
    )
    provider = AIProvider(
        metadata=ObjectMeta(name="storm", namespace=namespace),
        spec=AIProviderSpec(provider_id="storm", model_id="storm"),
    )
    await api.create("AIProvider", provider.to_dict())
    podmortem = Podmortem(
        metadata=ObjectMeta(name="storm", namespace=namespace),
        spec=PodmortemSpec(
            ai_provider_ref=AIProviderRef(name="storm", namespace=namespace),
        ),
    )
    await api.create("Podmortem", podmortem.to_dict())
    return StormStack(
        api=api, config=config, metrics=metrics, pipeline=pipeline,
        ledger=ledger, backend=backend, podmortem=podmortem,
        namespace=namespace, deadline_factor=deadline_factor,
        time_scale=time_scale,
    )


async def run_storm(
    stack: StormStack,
    process: ArrivalProcess,
    *,
    drain_s: float = 30.0,
) -> dict:
    """Drive one storm open-loop and fold the ledger's verdict into the
    driver's offered/achieved accounting — the record bench.py publishes
    as ``open_loop`` and the CI smoke asserts on."""
    report = await run_open_loop(
        stack.submit, process,
        time_scale=stack.time_scale, drain_s=drain_s,
    )
    snapshot = stack.ledger.snapshot()
    return {
        "arrival_spec": process.spec.to_dict(),
        "seed": process.seed,
        "fingerprint": process.fingerprint(),
        **report,
        "slo": snapshot,
        "fleet": stack.backend.fleet_view(),
    }
