"""The open-loop driver: fire arrivals on schedule, never wait in line.

``run_open_loop`` launches one task per :class:`~.arrivals.ArrivalEvent`
at its offset WITHOUT awaiting earlier completions — when the system
falls behind, arrivals keep coming and queues grow; that queueing
collapse is exactly what closed-loop benchmarks hide (docs/PERF.md).
After the last arrival, a bounded drain collects what it can; stragglers
past the drain budget are cancelled and counted (an operator reading the
report must see offered vs achieved diverge, never a silently shrunk
denominator).
"""

from __future__ import annotations

import asyncio
from typing import Any, Callable, Optional

from .arrivals import ArrivalEvent, ArrivalProcess

__all__ = ["run_open_loop"]


async def run_open_loop(
    submit: Callable[[ArrivalEvent], Any],
    process: ArrivalProcess,
    *,
    time_scale: float = 1.0,
    drain_s: float = 30.0,
) -> dict:
    """Drive ``submit(event)`` (an async callable owning its own ledger
    accounting) open-loop over the process's materialised schedule.

    ``time_scale`` compresses the schedule for smokes (0.1 = 10x faster
    than specified); the SCHEDULE itself is untouched — determinism is
    asserted on the materialised events, not on wall-clock.  Returns the
    offered/achieved accounting; SLO attainment lives in the caller's
    ledger."""
    events = process.materialize()
    loop = asyncio.get_running_loop()
    t0 = loop.time()
    tasks: list[asyncio.Task] = []
    for event in events:
        delay = event.at_s * time_scale - (loop.time() - t0)
        if delay > 0:
            await asyncio.sleep(delay)
        # ensure_future, never await: the arrival process does not care
        # how far behind the system is
        tasks.append(asyncio.ensure_future(submit(event)))
    launched_span_s = max(loop.time() - t0, 1e-9)
    drained = cancelled = errored = 0
    if tasks:
        done, pending = await asyncio.wait(tasks, timeout=drain_s)
        for task in pending:
            task.cancel()
            cancelled += 1
        if pending:
            await asyncio.gather(*pending, return_exceptions=True)
        for task in done:
            if task.cancelled() or task.exception() is not None:
                errored += 1
            else:
                drained += 1
    wall_s = max(loop.time() - t0, 1e-9)
    scaled_duration = max(process.spec.duration_s * time_scale, 1e-9)
    return {
        "arrivals": len(events),
        "offered_per_min": round(len(events) * 60.0 / scaled_duration, 3),
        "achieved_per_min": round(drained * 60.0 / wall_s, 3),
        "launch_span_s": round(launched_span_s, 3),
        "wall_s": round(wall_s, 3),
        "drained": drained,
        "cancelled_at_drain": cancelled,
        "submit_errors": errored,
    }
