"""Seeded open-loop arrival processes.

An :class:`ArrivalProcess` turns an :class:`ArrivalSpec` + seed into a
concrete schedule of :class:`ArrivalEvent` — offsets, analysis sizes,
recall-hot flags, SLO classes — with EVERY random draw taken at build
time from one ``random.Random(seed)`` (the ``utils/faultinject.py``
``bernoulli`` discipline: no draw during the run, so two materialisations
of the same (spec, seed) are byte-identical regardless of scheduling,
wall-clock, or how far the system fell behind).  ``fingerprint()`` hashes
the materialised schedule; the bench and the CI smoke assert two-replay
equality on it.

Time-varying rates (storm bursts, diurnal ramps) use Lewis-Shedler
thinning over the peak rate: candidate gaps are exponential at the peak,
each kept with probability ``rate(t)/peak`` — exact for piecewise and
sinusoidal rate functions alike, and every accept/reject is one more
build-time draw.
"""

from __future__ import annotations

import hashlib
import json
import math
import random
from dataclasses import asdict, dataclass, field
from typing import Optional

__all__ = ["ArrivalEvent", "ArrivalProcess", "ArrivalSpec"]


@dataclass(frozen=True)
class ArrivalEvent:
    """One offered failure: fired at ``at_s`` from storm start whether or
    not anything earlier has completed (open loop)."""

    index: int
    at_s: float
    kind: str  # "short" | "long" — analysis size (log volume)
    recall_hot: bool  # repeats a known failure class (recall hit) vs cold
    slo_class: str

    def to_dict(self) -> dict:
        return {
            "index": self.index,
            "at_s": round(self.at_s, 9),
            "kind": self.kind,
            "recall_hot": self.recall_hot,
            "slo_class": self.slo_class,
        }


@dataclass(frozen=True)
class ArrivalSpec:
    """Shape of the offered load.  ``name`` picks the rate function:

    - ``poisson`` — constant ``rate_per_min``;
    - ``storm``   — baseline with ``burst_factor``x bursts of
      ``burst_len_s`` every ``burst_every_s`` (correlated fleet-wide
      failure storms, the scenario vocabulary's disconnect/409-storm
      shape applied to arrivals);
    - ``diurnal`` — sinusoidal ramp, ``amplitude`` modulation over
      ``period_s``.

    ``class_mix`` weights are normalised; mean offered rate stays
    ``rate_per_min`` for poisson/diurnal, and for storm the bursts ADD
    load on top of the baseline (offered > nominal — the overload is the
    experiment)."""

    name: str = "storm"
    rate_per_min: float = 100.0
    duration_s: float = 60.0
    burst_factor: float = 4.0
    burst_every_s: float = 20.0
    burst_len_s: float = 5.0
    period_s: float = 60.0
    amplitude: float = 0.5
    long_fraction: float = 0.25
    recall_hot_fraction: float = 0.5
    class_mix: "tuple[tuple[str, float], ...]" = (
        ("interactive", 0.5), ("standard", 0.3), ("batch", 0.2),
    )

    def to_dict(self) -> dict:
        out = asdict(self)
        out["class_mix"] = [list(pair) for pair in self.class_mix]
        return out


@dataclass
class ArrivalProcess:
    spec: ArrivalSpec
    seed: int = 0
    _events: Optional["list[ArrivalEvent]"] = field(default=None, repr=False)

    def rate_per_s(self, t: float) -> float:
        spec = self.spec
        base = spec.rate_per_min / 60.0
        if spec.name == "storm":
            in_burst = (t % spec.burst_every_s) < spec.burst_len_s
            return base * (spec.burst_factor if in_burst else 1.0)
        if spec.name == "diurnal":
            phase = 2.0 * math.pi * t / max(spec.period_s, 1e-9)
            return base * max(0.0, 1.0 + spec.amplitude * math.sin(phase))
        return base

    def _peak_rate_per_s(self) -> float:
        spec = self.spec
        base = spec.rate_per_min / 60.0
        if spec.name == "storm":
            return base * max(1.0, spec.burst_factor)
        if spec.name == "diurnal":
            return base * (1.0 + max(0.0, spec.amplitude))
        return base

    def materialize(self) -> "list[ArrivalEvent]":
        """The full schedule, every draw taken NOW from one seeded rng.
        Cached: repeated calls (the driver, the fingerprint, the report)
        see one identical list."""
        if self._events is not None:
            return self._events
        spec = self.spec
        rng = random.Random(self.seed)
        peak = self._peak_rate_per_s()
        mix = [(name, max(0.0, weight)) for name, weight in spec.class_mix]
        total_weight = sum(w for _, w in mix) or 1.0
        events: list[ArrivalEvent] = []
        t = 0.0
        index = 0
        while peak > 0.0:
            t += rng.expovariate(peak)
            if t >= spec.duration_s:
                break
            # thinning accept/reject — one build-time draw per candidate
            if rng.random() * peak > self.rate_per_s(t):
                continue
            kind = "long" if rng.random() < spec.long_fraction else "short"
            recall_hot = rng.random() < spec.recall_hot_fraction
            pick = rng.random() * total_weight
            slo_class = mix[-1][0]
            for name, weight in mix:
                pick -= weight
                if pick <= 0.0:
                    slo_class = name
                    break
            events.append(ArrivalEvent(
                index=index, at_s=t, kind=kind,
                recall_hot=recall_hot, slo_class=slo_class,
            ))
            index += 1
        self._events = events
        return events

    def offered_per_min(self) -> float:
        events = self.materialize()
        span = max(self.spec.duration_s, 1e-9)
        return len(events) * 60.0 / span

    def fingerprint(self) -> str:
        """sha256 over the spec + the materialised schedule — equal
        fingerprints mean byte-identical replays (the two-replay gate
        bench.py and the CI smoke assert)."""
        basis = {
            "spec": self.spec.to_dict(),
            "seed": self.seed,
            "events": [e.to_dict() for e in self.materialize()],
        }
        return hashlib.sha256(
            json.dumps(basis, sort_keys=True).encode()
        ).hexdigest()
