"""Deterministic open-loop load generation (docs/PERF.md "Open-loop
methodology").

``arrivals.py`` builds seeded arrival schedules — Poisson baseline,
failure-storm bursts, diurnal ramps — with EVERY random draw materialised
at build time (the ``utils/faultinject.py`` discipline), so the same
(spec, seed) replays byte-identically; ``driver.py`` fires them open-loop
(arrivals keep coming when the system falls behind — that is the point);
``storm.py`` assembles the in-process operator→router→serving stack the
storm drives, shared by ``bench.py`` and the CI smoke
(``python -m operator_tpu.loadgen``).
"""

from __future__ import annotations

from .arrivals import ArrivalEvent, ArrivalProcess, ArrivalSpec
from .driver import run_open_loop
from .storm import StormStack, build_storm_stack, run_storm

__all__ = [
    "ArrivalEvent",
    "ArrivalProcess",
    "ArrivalSpec",
    "StormStack",
    "build_storm_stack",
    "run_open_loop",
    "run_storm",
]
