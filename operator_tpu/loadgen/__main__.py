"""CI chaos smoke: ``python -m operator_tpu.loadgen``.

Runs a short compressed failure storm through the full in-process
operator→router→serving stack (synthetic replicas — no JAX) and FAILS
LOUDLY unless:

- the open-loop record is populated (arrivals landed, the ledger settled
  every one of them — admitted == terminal, nothing leaked pending);
- the ledger journal has ZERO torn lines (every line parses back);
- the arrival schedule replays byte-identically (two independent
  materialisations, equal fingerprints).

With ``LOADGEN_OVERLOAD=1`` it instead runs the 2×-collapse overload
pass (router/value.py, docs/ROBUSTNESS.md "Degradation ladder"):
``storm.simulate_overload`` replays the seeded arrival schedule against
a virtual-clock queue through the PRODUCTION ``OverloadPolicy`` /
``ValueModel`` — deterministic by construction, so the gate means the
same thing on an idle laptop and a thrashing CI runner (a live-stack
gate flakes both ways: a fast host never overloads, a contended one
cliffs on wall-clock targets regardless of the ladder).  The sweep runs
0.5×..2× around the collapse rate (``LOADGEN_COLLAPSE_RATE_PER_MIN``,
default 900) and is gated on

- NO-CLIFF decay: total and per-class attainment between adjacent
  sweep points never drops more than ``LOADGEN_MAX_ATTAINMENT_STEP``;
- ZERO value-shed events in any protected class (below its attainment
  target at decision time) anywhere in the sweep;
- the ladder actually engaged at 2× (degraded or shed something) —
  a sweep that never overloads would make both gates hollow;
- byte-identical replay: the 2× point re-run with the same seed must
  reproduce the identical result row and decision-log sha256 (GL007).

Exit code 0 = all gates green; 1 = a gate failed (printed to stderr).
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import tempfile

from .arrivals import ArrivalProcess, ArrivalSpec
from .storm import (
    SyntheticReplica,
    build_storm_stack,
    run_storm,
    simulate_overload,
)


def _fail(msg: str) -> None:
    print(f"loadgen smoke: FAIL: {msg}", file=sys.stderr)
    raise SystemExit(1)


async def _main() -> None:
    seed = int(os.environ.get("LOADGEN_SEED", "0") or 0)
    spec = ArrivalSpec(
        name="storm",
        rate_per_min=float(os.environ.get("LOADGEN_SMOKE_RATE_PER_MIN", "240")),
        duration_s=float(os.environ.get("LOADGEN_SMOKE_DURATION_S", "5")),
        burst_factor=4.0,
        burst_every_s=2.0,
        burst_len_s=0.5,
    )
    process = ArrivalProcess(spec, seed=seed)

    # gate 3 first (cheap): an independent second materialisation of the
    # same (spec, seed) must replay byte-identically
    replay = ArrivalProcess(spec, seed=seed)
    if process.fingerprint() != replay.fingerprint():
        _fail("arrival schedule is not replay-identical for one (spec, seed)")
    if [e.to_dict() for e in process.materialize()] != [
        e.to_dict() for e in replay.materialize()
    ]:
        _fail("fingerprints matched but materialised events differ")

    with tempfile.TemporaryDirectory(prefix="loadgen-smoke-") as tmp:
        ledger_path = os.path.join(tmp, "slo-ledger.jsonl")
        stack = await build_storm_stack(
            # undersized on purpose: the smoke should see real queueing,
            # not an idle system
            replicas=[
                SyntheticReplica("smoke-replica-0", concurrency=2,
                                 time_scale=0.2),
                SyntheticReplica("smoke-replica-1", concurrency=2,
                                 time_scale=0.2),
            ],
            time_scale=0.2,
            ledger_path=ledger_path,
        )
        report = await run_storm(stack, process, drain_s=20.0)
        stack.close()

        # gate 1: populated open-loop record, every arrival settled
        if report["arrivals"] <= 0:
            _fail("storm produced no arrivals")
        total = report["slo"]["total"]
        if total["admitted"] != report["arrivals"] - report["cancelled_at_drain"]:
            _fail(
                f"ledger admitted {total['admitted']} != "
                f"{report['arrivals']} arrivals - "
                f"{report['cancelled_at_drain']} cancelled"
            )
        if report["slo"]["pending"] != 0:
            _fail(f"{report['slo']['pending']} records leaked pending")
        if total["attainment"] is None:
            _fail("open_loop record has null attainment")
        if not report["slo"]["classes"]:
            _fail("no per-class rows in the SLO summary")

        # gate 2: zero torn ledger lines — every journaled line parses
        with open(ledger_path) as fh:
            raw_lines = [line for line in fh if line.strip()]
        parsed = 0
        for line in raw_lines:
            try:
                json.loads(line)
                parsed += 1
            except ValueError:
                _fail(f"torn ledger line: {line[:80]!r}")
        if parsed != total["admitted"]:
            _fail(f"journal has {parsed} lines, ledger settled {total['admitted']}")

    print(json.dumps({
        "arrivals": report["arrivals"],
        "offered_per_min": report["offered_per_min"],
        "achieved_per_min": report["achieved_per_min"],
        "attainment": total["attainment"],
        "shed": total["shed"],
        "deadline_exceeded": total["deadline_exceeded"],
        "goodput_analyses_per_min": total["goodput_analyses_per_min"],
        "fingerprint": report["fingerprint"][:16],
        "journal_lines": parsed,
    }, indent=2))
    print("loadgen smoke: OK")


def _engaged(row: dict) -> bool:
    return bool(row["shed_total"] or row["degraded_total"])


def _overload_main() -> None:
    """The 2×-collapse overload pass (LOADGEN_OVERLOAD=1).

    Pure virtual-time simulation (storm.simulate_overload) riding the
    production value ladder — no event loop, no wall clocks, so the
    gates below hold identically on any machine under any load."""
    seed = int(os.environ.get("LOADGEN_SEED", "0") or 0)
    duration = float(os.environ.get("LOADGEN_OVERLOAD_DURATION_S", "60"))
    max_step = float(os.environ.get("LOADGEN_MAX_ATTAINMENT_STEP", "0.15"))
    collapse = float(
        os.environ.get("LOADGEN_COLLAPSE_RATE_PER_MIN", "") or 900.0
    )

    rows: "list[dict]" = []
    for factor in (0.5, 0.75, 1.0, 1.5, 2.0):
        row = simulate_overload(
            collapse * factor, seed=seed, duration_s=duration,
        )
        row["factor"] = factor
        rows.append(row)

    # gate 1: NO-CLIFF — attainment decays smoothly across the sweep
    for prev, cur in zip(rows, rows[1:]):
        pairs = [("total", prev["attainment"], cur["attainment"])]
        for cls, prev_att in prev["attainment_by_class"].items():
            pairs.append((cls, prev_att, cur["attainment_by_class"].get(cls)))
        for name, a, b in pairs:
            if a is None or b is None:
                continue
            if a - b > max_step:
                _fail(
                    f"attainment CLIFF for {name}: {a} at "
                    f"{prev['factor']}x -> {b} at {cur['factor']}x "
                    f"(max smooth step {max_step})"
                )

    # gate 2: the ladder never value-shed a class that was protected at
    # decision time, anywhere in the sweep (the sim counts these causally)
    for row in rows:
        if row["protected_shed"]:
            _fail(
                f"{row['protected_shed']} protected-class requests were "
                f"value-shed at {row['factor']}x "
                f"({row['rate_per_min']:.0f}/min)"
            )

    # gate 3: the ladder ENGAGED at 2x — otherwise gates 1-2 are hollow
    peak = rows[-1]
    if not _engaged(peak):
        _fail(
            "overload ladder never fired at 2x collapse "
            f"({peak['rate_per_min']:.0f}/min) — raise "
            "LOADGEN_COLLAPSE_RATE_PER_MIN"
        )

    # gate 4: byte-identical replay of the 2x point (GL007) — same seed,
    # same knobs, identical result row INCLUDING the decision-log sha256
    replay = simulate_overload(
        collapse * 2.0, seed=seed, duration_s=duration,
    )
    replay["factor"] = 2.0
    if replay != peak:
        drift = [
            k for k in sorted(set(peak) | set(replay))
            if peak.get(k) != replay.get(k)
        ]
        _fail(f"2x overload replay is not byte-identical: {drift} differ")

    for row in rows:
        row.pop("decision_log", None)  # sha is printed; the text is bulky
    print(json.dumps(rows, indent=2))
    print("loadgen overload: OK")


if __name__ == "__main__":
    if os.environ.get("LOADGEN_OVERLOAD", "0") == "1":
        _overload_main()
    else:
        asyncio.run(_main())
