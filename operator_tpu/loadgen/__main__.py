"""CI chaos smoke: ``python -m operator_tpu.loadgen``.

Runs a short compressed failure storm through the full in-process
operator→router→serving stack (synthetic replicas — no JAX) and FAILS
LOUDLY unless:

- the open-loop record is populated (arrivals landed, the ledger settled
  every one of them — admitted == terminal, nothing leaked pending);
- the ledger journal has ZERO torn lines (every line parses back);
- the arrival schedule replays byte-identically (two independent
  materialisations, equal fingerprints).

With ``LOADGEN_OVERLOAD=1`` it instead runs the 2×-collapse overload
pass (router/value.py, docs/ROBUSTNESS.md "Degradation ladder"):
``storm.simulate_overload`` replays the seeded arrival schedule against
a virtual-clock queue through the PRODUCTION ``OverloadPolicy`` /
``ValueModel`` — deterministic by construction, so the gate means the
same thing on an idle laptop and a thrashing CI runner (a live-stack
gate flakes both ways: a fast host never overloads, a contended one
cliffs on wall-clock targets regardless of the ladder).  The sweep runs
0.5×..2× around the collapse rate (``LOADGEN_COLLAPSE_RATE_PER_MIN``,
default 900) and is gated on

- NO-CLIFF decay: total and per-class attainment between adjacent
  sweep points never drops more than ``LOADGEN_MAX_ATTAINMENT_STEP``;
- ZERO value-shed events in any protected class (below its attainment
  target at decision time) anywhere in the sweep;
- the ladder actually engaged at 2× (degraded or shed something) —
  a sweep that never overloads would make both gates hollow;
- byte-identical replay: the 2× point re-run with the same seed must
  reproduce the identical result row and decision-log sha256 (GL007).

Exit code 0 = all gates green; 1 = a gate failed (printed to stderr).
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import tempfile

from .arrivals import ArrivalProcess, ArrivalSpec
from .storm import (
    SyntheticReplica,
    build_storm_stack,
    run_storm,
    simulate_overload,
)


def _fail(msg: str) -> None:
    print(f"loadgen smoke: FAIL: {msg}", file=sys.stderr)
    raise SystemExit(1)


async def _main() -> None:
    seed = int(os.environ.get("LOADGEN_SEED", "0") or 0)
    spec = ArrivalSpec(
        name="storm",
        rate_per_min=float(os.environ.get("LOADGEN_SMOKE_RATE_PER_MIN", "240")),
        duration_s=float(os.environ.get("LOADGEN_SMOKE_DURATION_S", "5")),
        burst_factor=4.0,
        burst_every_s=2.0,
        burst_len_s=0.5,
    )
    process = ArrivalProcess(spec, seed=seed)

    # gate 3 first (cheap): an independent second materialisation of the
    # same (spec, seed) must replay byte-identically
    replay = ArrivalProcess(spec, seed=seed)
    if process.fingerprint() != replay.fingerprint():
        _fail("arrival schedule is not replay-identical for one (spec, seed)")
    if [e.to_dict() for e in process.materialize()] != [
        e.to_dict() for e in replay.materialize()
    ]:
        _fail("fingerprints matched but materialised events differ")

    with tempfile.TemporaryDirectory(prefix="loadgen-smoke-") as tmp:
        ledger_path = os.path.join(tmp, "slo-ledger.jsonl")
        stack = await build_storm_stack(
            # undersized on purpose: the smoke should see real queueing,
            # not an idle system
            replicas=[
                SyntheticReplica("smoke-replica-0", concurrency=2,
                                 time_scale=0.2),
                SyntheticReplica("smoke-replica-1", concurrency=2,
                                 time_scale=0.2),
            ],
            time_scale=0.2,
            ledger_path=ledger_path,
        )
        report = await run_storm(stack, process, drain_s=20.0)
        stack.close()

        # gate 1: populated open-loop record, every arrival settled
        if report["arrivals"] <= 0:
            _fail("storm produced no arrivals")
        total = report["slo"]["total"]
        if total["admitted"] != report["arrivals"] - report["cancelled_at_drain"]:
            _fail(
                f"ledger admitted {total['admitted']} != "
                f"{report['arrivals']} arrivals - "
                f"{report['cancelled_at_drain']} cancelled"
            )
        if report["slo"]["pending"] != 0:
            _fail(f"{report['slo']['pending']} records leaked pending")
        if total["attainment"] is None:
            _fail("open_loop record has null attainment")
        if not report["slo"]["classes"]:
            _fail("no per-class rows in the SLO summary")

        # gate 2: zero torn ledger lines — every journaled line parses
        with open(ledger_path) as fh:
            raw_lines = [line for line in fh if line.strip()]
        parsed = 0
        for line in raw_lines:
            try:
                json.loads(line)
                parsed += 1
            except ValueError:
                _fail(f"torn ledger line: {line[:80]!r}")
        if parsed != total["admitted"]:
            _fail(f"journal has {parsed} lines, ledger settled {total['admitted']}")

    print(json.dumps({
        "arrivals": report["arrivals"],
        "offered_per_min": report["offered_per_min"],
        "achieved_per_min": report["achieved_per_min"],
        "attainment": total["attainment"],
        "shed": total["shed"],
        "deadline_exceeded": total["deadline_exceeded"],
        "goodput_analyses_per_min": total["goodput_analyses_per_min"],
        "fingerprint": report["fingerprint"][:16],
        "journal_lines": parsed,
    }, indent=2))
    print("loadgen smoke: OK")


async def _disagg_main() -> None:
    """The prefill/decode disaggregation smoke (LOADGEN_DISAGG=1,
    docs/FABRIC.md).

    A seeded storm runs against a 1-prefill + 2-decode synthetic fleet
    with the backend in disaggregated dispatch: every analysis is a
    prefill leg routed role=prefill plus a decode leg routed
    role=decode (fabric/disagg.py).  Gates: byte-identical arrival
    replay (two independent materialisations), every arrival settled
    with nothing leaked pending and zero torn ledger lines, the
    disaggregation actually happened (handoff counter fired, the
    prefill replica served prefill legs, the decode replicas served
    decode legs), and the fleet rollup carries the per-role tiers the
    autoscaler keys on."""
    seed = int(os.environ.get("LOADGEN_SEED", "0") or 0)
    time_scale = 0.2
    spec = ArrivalSpec(
        name="disagg",
        rate_per_min=float(
            os.environ.get("LOADGEN_DISAGG_RATE_PER_MIN", "240")
        ),
        duration_s=float(os.environ.get("LOADGEN_DISAGG_DURATION_S", "4")),
        burst_factor=3.0,
        burst_every_s=2.0,
        burst_len_s=0.5,
    )
    process = ArrivalProcess(spec, seed=seed)

    # replay gate first: two independent materialisations of the same
    # (spec, seed) must be byte-identical
    replay = ArrivalProcess(spec, seed=seed)
    if process.fingerprint() != replay.fingerprint():
        _fail("disagg arrival schedule is not replay-identical")
    if [e.to_dict() for e in process.materialize()] != [
        e.to_dict() for e in replay.materialize()
    ]:
        _fail("fingerprints matched but materialised events differ")

    with tempfile.TemporaryDirectory(prefix="loadgen-disagg-") as tmp:
        ledger_path = os.path.join(tmp, "slo-ledger.jsonl")
        fleet = [
            SyntheticReplica("disagg-prefill-0", concurrency=2,
                             time_scale=time_scale, role="prefill"),
            SyntheticReplica("disagg-decode-0", concurrency=2,
                             time_scale=time_scale, role="decode"),
            SyntheticReplica("disagg-decode-1", concurrency=2,
                             time_scale=time_scale, role="decode"),
        ]
        stack = await build_storm_stack(
            replicas=fleet, time_scale=time_scale,
            ledger_path=ledger_path, disaggregate=True,
        )
        report = await run_storm(stack, process, drain_s=20.0)
        stack.close()

        # gate: populated record, every arrival settled, nothing pending
        if report["arrivals"] <= 0:
            _fail("disagg storm produced no arrivals")
        total = report["slo"]["total"]
        if total["admitted"] != report["arrivals"] - report["cancelled_at_drain"]:
            _fail(
                f"ledger admitted {total['admitted']} != "
                f"{report['arrivals']} arrivals - "
                f"{report['cancelled_at_drain']} cancelled"
            )
        if report["slo"]["pending"] != 0:
            _fail(f"{report['slo']['pending']} records leaked pending")
        if total["attainment"] is None:
            _fail("disagg storm record has null attainment")

        # gate: zero torn ledger lines
        with open(ledger_path) as fh:
            for line in fh:
                if not line.strip():
                    continue
                try:
                    json.loads(line)
                except ValueError:
                    _fail(f"torn ledger line: {line[:80]!r}")

        # gate: disaggregation actually happened, on the right tiers
        handoffs = stack.metrics.counter("fabric_disagg_handoff")
        if handoffs <= 0:
            _fail("no fabric_disagg_handoff recorded — the backend never "
                  "split a request into prefill+decode legs")
        prefill_replica, *decode_replicas = fleet
        if prefill_replica.served_by_phase.get("prefill", 0) <= 0:
            _fail("the prefill replica served no prefill legs")
        if sum(r.served_by_phase.get("decode", 0)
               for r in decode_replicas) <= 0:
            _fail("the decode replicas served no decode legs")
        # role preference, not filter: prefill legs stay OFF the decode
        # tier while the prefill replica is healthy (and vice versa)
        if any(r.served_by_phase.get("prefill", 0) > 0
               for r in decode_replicas) and \
                prefill_replica.served_by_phase.get("decode", 0) > 0:
            _fail("both tiers crossed roles despite healthy exact-role "
                  "candidates — role preference is not being applied")

        # gate: the fleet rollup carries per-role tiers
        roles = (report["fleet"].get("fleet") or {}).get("roles") or {}
        if "prefill" not in roles or "decode" not in roles:
            _fail(f"fleet rollup missing role tiers: {sorted(roles)}")
        if roles["prefill"]["replicas"] != 1 or roles["decode"]["replicas"] != 2:
            _fail(f"role tier shape wrong: {roles}")

    print(json.dumps({
        "arrivals": report["arrivals"],
        "attainment": total["attainment"],
        "goodput_analyses_per_min": total["goodput_analyses_per_min"],
        "handoffs": handoffs,
        "prefill_legs": prefill_replica.served_by_phase,
        "decode_legs": [r.served_by_phase for r in decode_replicas],
        "fingerprint": report["fingerprint"][:16],
    }, indent=2))
    print("loadgen disagg: OK")


async def _elastic_main() -> None:
    """The scale-to-zero-and-back elastic smoke (LOADGEN_ELASTIC=1).

    The fleet starts at ZERO replicas.  A seeded storm arrives; the
    autoscaler (operator/autoscale.py) sees the pending admissions and
    scales the fake Deployment up through the scale subresource; a tiny
    in-process "deployment controller" turns spec.replicas into Endpoints
    addresses; the endpoint watch (router/discovery.py) turns those into
    live ring members serving the very arrivals that woke the fleet.
    When the storm drains, the idle window elapses and the fleet scales
    back to zero.  Gates: byte-identical arrival replay (twice), every
    arrival settled with zero torn ledger lines, the fleet actually made
    the 0→N→0 round trip, and the membership/autoscale counters fired.
    """
    from ..operator.autoscale import AutoscaleController
    from ..router.discovery import EndpointDiscovery

    seed = int(os.environ.get("LOADGEN_SEED", "0") or 0)
    time_scale = 0.2
    spec = ArrivalSpec(
        name="elastic",
        rate_per_min=float(os.environ.get("LOADGEN_ELASTIC_RATE_PER_MIN", "300")),
        duration_s=float(os.environ.get("LOADGEN_ELASTIC_DURATION_S", "4")),
        burst_factor=3.0,
        burst_every_s=2.0,
        burst_len_s=0.5,
    )
    process = ArrivalProcess(spec, seed=seed)

    # replay gate first: two independent materialisations of the same
    # (spec, seed) must be byte-identical — the elastic storm is as
    # replayable as the static one
    replay = ArrivalProcess(spec, seed=seed)
    if process.fingerprint() != replay.fingerprint():
        _fail("elastic arrival schedule is not replay-identical")
    if [e.to_dict() for e in process.materialize()] != [
        e.to_dict() for e in replay.materialize()
    ]:
        _fail("fingerprints matched but materialised events differ")

    with tempfile.TemporaryDirectory(prefix="loadgen-elastic-") as tmp:
        ledger_path = os.path.join(tmp, "slo-ledger.jsonl")
        # replicas=[] — the fleet REALLY starts empty (scale-from-zero)
        stack = await build_storm_stack(
            replicas=[], time_scale=time_scale, ledger_path=ledger_path,
        )
        api, backend, ns = stack.api, stack.backend, stack.namespace
        deployment = "podmortem-serving"
        await api.create("Deployment", {
            "apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": {"name": deployment, "namespace": ns},
            "spec": {"replicas": 0},
        })
        await api.create("Endpoints", {
            "apiVersion": "v1", "kind": "Endpoints",
            "metadata": {"name": deployment, "namespace": ns},
            "subsets": [],
        })

        class RingAdapter:
            """EngineRouter facade for the discovery loop: a joining
            endpoint becomes a live synthetic replica in the storm
            backend (which pulses the wake event arrivals wait on)."""

            def add(self, replica) -> None:
                backend.add_replica(SyntheticReplica(
                    replica.id, concurrency=2, time_scale=time_scale,
                ))

            def remove(self, replica_id: str) -> None:
                backend.remove_replica(replica_id)

        discovery = EndpointDiscovery(
            api, RingAdapter(), service=deployment, namespace=ns,
            kube_timeout_s=5.0, restart_delay_s=0.05,
        )
        autoscaler = AutoscaleController(
            api, deployment=deployment, namespace=ns,
            min_replicas=0, max_replicas=4, target_pressure=4.0,
            idle_s=0.5, interval_s=0.05, kube_timeout_s=5.0,
            fleet=lambda: backend.fleet_view()["fleet"],
            attainment=stack.ledger.attainment_by_class,
            pending=lambda: stack.ledger.pending,
            metrics=stack.metrics,
        )

        stop = asyncio.Event()
        peak = 0

        async def actuate() -> None:
            # the in-process "deployment controller": spec.replicas
            # becomes ready Endpoints addresses, like kubelets turning
            # pods Ready behind the headless Service
            known = -1
            while not stop.is_set():
                try:
                    scale = await api.get_scale("Deployment", deployment, ns)
                    desired = int(scale["spec"]["replicas"])
                    if desired != known:
                        subsets = [{
                            "addresses": [
                                {"ip": f"10.0.0.{i + 1}"}
                                for i in range(desired)
                            ],
                            "ports": [{"name": "http", "port": 8000}],
                        }] if desired else []
                        await api.patch("Endpoints", deployment, ns,
                                        {"subsets": subsets})
                        known = desired
                except Exception:  # noqa: BLE001 - reconcile again next tick
                    pass
                await asyncio.sleep(0.03)

        async def monitor() -> None:
            nonlocal peak
            while not stop.is_set():
                peak = max(peak, len(backend.router))
                await asyncio.sleep(0.02)

        tasks = [asyncio.create_task(coro) for coro in (
            discovery.run(stop), autoscaler.run(stop), actuate(), monitor(),
        )]
        settled_to_zero = False
        try:
            report = await run_storm(stack, process, drain_s=20.0)
            # the round trip's back half: idle window elapses, the fleet
            # scales to zero and the ring empties (bounded wait, no gate
            # on exact timing)
            for _ in range(300):
                scale = await api.get_scale("Deployment", deployment, ns)
                if (int(scale["spec"]["replicas"]) == 0
                        and len(backend.router) == 0):
                    settled_to_zero = True
                    break
                await asyncio.sleep(0.05)
        finally:
            stop.set()
            api.close_watches()  # unblocks the discovery watch stream
            for task in tasks:
                task.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
        stack.close()

        # gate: every arrival settled, nothing leaked, nothing torn
        if report["arrivals"] <= 0:
            _fail("elastic storm produced no arrivals")
        total = report["slo"]["total"]
        if total["admitted"] != report["arrivals"] - report["cancelled_at_drain"]:
            _fail(
                f"ledger admitted {total['admitted']} != "
                f"{report['arrivals']} arrivals - "
                f"{report['cancelled_at_drain']} cancelled"
            )
        if report["slo"]["pending"] != 0:
            _fail(f"{report['slo']['pending']} records leaked pending")
        with open(ledger_path) as fh:
            raw_lines = [line for line in fh if line.strip()]
        parsed = 0
        for line in raw_lines:
            try:
                json.loads(line)
                parsed += 1
            except ValueError:
                _fail(f"torn ledger line: {line[:80]!r}")
        if parsed != total["admitted"]:
            _fail(f"journal has {parsed} lines, ledger settled "
                  f"{total['admitted']}")

        # gate: the fleet made the 0→N→0 round trip
        if peak < 1:
            _fail("fleet never scaled up from zero (peak membership 0)")
        if not settled_to_zero:
            _fail("fleet never scaled back to zero after the storm drained")
        counters = stack.metrics.snapshot()["counters"]
        for name in ("autoscale_up", "autoscale_to_zero",
                     "ring_member_added", "ring_member_removed"):
            if counters.get(name, 0) < 1:
                _fail(f"counter {name} never fired (got "
                      f"{counters.get(name, 0)})")

    print(json.dumps({
        "arrivals": report["arrivals"],
        "attainment": total["attainment"],
        "attainment_by_class": report["overload"]["attainment_by_class"]
        if report.get("overload") else None,
        "peak_fleet": peak,
        "scaled_to_zero": settled_to_zero,
        "autoscale_up": counters.get("autoscale_up", 0),
        "autoscale_down": counters.get("autoscale_down", 0),
        "autoscale_to_zero": counters.get("autoscale_to_zero", 0),
        "ring_member_added": counters.get("ring_member_added", 0),
        "ring_member_removed": counters.get("ring_member_removed", 0),
        "fingerprint": report["fingerprint"][:16],
        "journal_lines": parsed,
    }, indent=2))
    print("loadgen elastic: OK")


def _engaged(row: dict) -> bool:
    return bool(row["shed_total"] or row["degraded_total"])


def _overload_main() -> None:
    """The 2×-collapse overload pass (LOADGEN_OVERLOAD=1).

    Pure virtual-time simulation (storm.simulate_overload) riding the
    production value ladder — no event loop, no wall clocks, so the
    gates below hold identically on any machine under any load."""
    seed = int(os.environ.get("LOADGEN_SEED", "0") or 0)
    duration = float(os.environ.get("LOADGEN_OVERLOAD_DURATION_S", "60"))
    max_step = float(os.environ.get("LOADGEN_MAX_ATTAINMENT_STEP", "0.15"))
    collapse = float(
        os.environ.get("LOADGEN_COLLAPSE_RATE_PER_MIN", "") or 900.0
    )

    rows: "list[dict]" = []
    for factor in (0.5, 0.75, 1.0, 1.5, 2.0):
        row = simulate_overload(
            collapse * factor, seed=seed, duration_s=duration,
        )
        row["factor"] = factor
        rows.append(row)

    # gate 1: NO-CLIFF — attainment decays smoothly across the sweep
    for prev, cur in zip(rows, rows[1:]):
        pairs = [("total", prev["attainment"], cur["attainment"])]
        for cls, prev_att in prev["attainment_by_class"].items():
            pairs.append((cls, prev_att, cur["attainment_by_class"].get(cls)))
        for name, a, b in pairs:
            if a is None or b is None:
                continue
            if a - b > max_step:
                _fail(
                    f"attainment CLIFF for {name}: {a} at "
                    f"{prev['factor']}x -> {b} at {cur['factor']}x "
                    f"(max smooth step {max_step})"
                )

    # gate 2: the ladder never value-shed a class that was protected at
    # decision time, anywhere in the sweep (the sim counts these causally)
    for row in rows:
        if row["protected_shed"]:
            _fail(
                f"{row['protected_shed']} protected-class requests were "
                f"value-shed at {row['factor']}x "
                f"({row['rate_per_min']:.0f}/min)"
            )

    # gate 3: the ladder ENGAGED at 2x — otherwise gates 1-2 are hollow
    peak = rows[-1]
    if not _engaged(peak):
        _fail(
            "overload ladder never fired at 2x collapse "
            f"({peak['rate_per_min']:.0f}/min) — raise "
            "LOADGEN_COLLAPSE_RATE_PER_MIN"
        )

    # gate 4: byte-identical replay of the 2x point (GL007) — same seed,
    # same knobs, identical result row INCLUDING the decision-log sha256
    replay = simulate_overload(
        collapse * 2.0, seed=seed, duration_s=duration,
    )
    replay["factor"] = 2.0
    if replay != peak:
        drift = [
            k for k in sorted(set(peak) | set(replay))
            if peak.get(k) != replay.get(k)
        ]
        _fail(f"2x overload replay is not byte-identical: {drift} differ")

    for row in rows:
        row.pop("decision_log", None)  # sha is printed; the text is bulky
    print(json.dumps(rows, indent=2))
    print("loadgen overload: OK")


async def _gameday_main() -> None:
    """``LOADGEN_GAMEDAY=1``: the seeded game-day matrix
    (docs/ROBUSTNESS.md "Game days").

    Runs each scenario through the chaos conductor
    (``operator_tpu/chaos/``) and fails loudly unless, per scenario:

    - BUILD determinism: an independent second build and a JSON
      round-trip both produce the identical fingerprint — the replay
      contract a committed repro depends on;
    - the invariant auditor recorded ZERO violations across its commit
      barriers and the scenario-end sweep;
    - every injection fired (``pending_faults == {}``) — a rule the run
      never consumed is a renamed seam or a dead phase window, and a
      gate that ignores it quietly stops rehearsing that failure;
    - arrivals landed and every submit drained without error.

    Scenario selection: the builtin matrix (``chaos/library.py``,
    reseeded by ``LOADGEN_SEED``) plus every committed repro under
    ``tests/scenarios/*.json``; ``LOADGEN_SCENARIO=<file.json>`` runs
    that one file instead — the replay path printed by the shrinker.
    ``LOADGEN_MUTATION=<name>`` arms a mutation lane (the auditor
    self-test), inverting the violation gate: the run must violate.
    """
    from ..chaos import ChaosScenario, run_scenario
    from ..chaos.library import builtin_scenarios
    from ..utils.timing import MetricsRegistry

    seed = int(os.environ.get("LOADGEN_SEED", "0") or 0)
    mutation = os.environ.get("LOADGEN_MUTATION") or None
    single = os.environ.get("LOADGEN_SCENARIO") or None

    # (scenario, source, rebuild): rebuild() is the INDEPENDENT second
    # build the fingerprint-identity gate compares against
    jobs = []
    if single:
        try:
            with open(single, encoding="utf-8") as fh:
                text = fh.read()
            scenario = ChaosScenario.from_json(text)
        except (OSError, ValueError, KeyError) as exc:
            _fail(f"cannot load LOADGEN_SCENARIO={single}: {exc}")
        jobs.append((
            scenario, single,
            lambda text=text: ChaosScenario.from_json(text),
        ))
    else:
        for i, scenario in enumerate(builtin_scenarios(seed)):
            jobs.append((
                scenario, "builtin",
                lambda i=i: builtin_scenarios(seed)[i],
            ))
        scen_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))),
            "tests", "scenarios",
        )
        if os.path.isdir(scen_dir):
            for name in sorted(os.listdir(scen_dir)):
                if not name.endswith(".json"):
                    continue
                path = os.path.join(scen_dir, name)
                with open(path, encoding="utf-8") as fh:
                    text = fh.read()
                try:
                    scenario = ChaosScenario.from_json(text)
                except (ValueError, KeyError) as exc:
                    _fail(f"committed scenario {name} does not load: {exc}")
                jobs.append((
                    scenario, f"tests/scenarios/{name}",
                    lambda text=text: ChaosScenario.from_json(text),
                ))

    rows = []
    for scenario, source, rebuild in jobs:
        where = f"{scenario.name} ({source})"
        if rebuild().fingerprint() != scenario.fingerprint():
            _fail(f"{where}: two builds disagree on the fingerprint")
        round_trip = ChaosScenario.from_json(scenario.to_json())
        if round_trip.fingerprint() != scenario.fingerprint():
            _fail(f"{where}: JSON round-trip changes the fingerprint")

        report = await run_scenario(
            scenario, mutation=mutation, metrics=MetricsRegistry(),
        )
        violated = [v["name"] for v in report["violations"]]
        if mutation is None and violated:
            _fail(
                f"{where}: invariant violation(s) {violated} — black-boxed "
                "by the flight recorder; shrink the scenario to a minimal "
                "repro with operator_tpu.chaos.shrink"
            )
        if mutation is not None and not violated:
            _fail(
                f"{where}: mutation `{mutation}` armed but no invariant "
                "fired — the auditor is asleep"
            )
        if report["pending_faults"]:
            _fail(
                f"{where}: injections never fired: "
                f"{report['pending_faults']} — renamed seam or dead "
                "phase window"
            )
        driver = report["driver"]
        if not driver["arrivals"]:
            _fail(f"{where}: no arrivals landed")
        if driver["submit_errors"] or driver["cancelled_at_drain"]:
            _fail(
                f"{where}: {driver['submit_errors']} submit error(s), "
                f"{driver['cancelled_at_drain']} cancelled at drain"
            )
        rows.append({
            "scenario": scenario.name,
            "source": source,
            "seed": scenario.seed,
            "fingerprint": report["fingerprint"],
            "arrivals": driver["arrivals"],
            "completed": report["slo"]["total"]["completed"],
            "invariant_checks": report["invariant_checks"],
            "violations": violated,
            "fault_trace_len": report["fault_trace_len"],
            "actions": len(report["actions"]),
        })

    print(json.dumps(rows, indent=2))
    print("loadgen gameday: OK")


if __name__ == "__main__":
    if os.environ.get("LOADGEN_OVERLOAD", "0") == "1":
        _overload_main()
    elif os.environ.get("LOADGEN_ELASTIC", "0") == "1":
        asyncio.run(_elastic_main())
    elif os.environ.get("LOADGEN_DISAGG", "0") == "1":
        asyncio.run(_disagg_main())
    elif os.environ.get("LOADGEN_GAMEDAY", "0") == "1":
        asyncio.run(_gameday_main())
    else:
        asyncio.run(_main())
