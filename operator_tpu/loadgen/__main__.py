"""CI chaos smoke: ``python -m operator_tpu.loadgen``.

Runs a short compressed failure storm through the full in-process
operator→router→serving stack (synthetic replicas — no JAX) and FAILS
LOUDLY unless:

- the open-loop record is populated (arrivals landed, the ledger settled
  every one of them — admitted == terminal, nothing leaked pending);
- the ledger journal has ZERO torn lines (every line parses back);
- the arrival schedule replays byte-identically (two independent
  materialisations, equal fingerprints).

Exit code 0 = all gates green; 1 = a gate failed (printed to stderr).
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import tempfile

from .arrivals import ArrivalProcess, ArrivalSpec
from .storm import SyntheticReplica, build_storm_stack, run_storm


def _fail(msg: str) -> None:
    print(f"loadgen smoke: FAIL: {msg}", file=sys.stderr)
    raise SystemExit(1)


async def _main() -> None:
    seed = int(os.environ.get("LOADGEN_SEED", "0") or 0)
    spec = ArrivalSpec(
        name="storm",
        rate_per_min=float(os.environ.get("LOADGEN_SMOKE_RATE_PER_MIN", "240")),
        duration_s=float(os.environ.get("LOADGEN_SMOKE_DURATION_S", "5")),
        burst_factor=4.0,
        burst_every_s=2.0,
        burst_len_s=0.5,
    )
    process = ArrivalProcess(spec, seed=seed)

    # gate 3 first (cheap): an independent second materialisation of the
    # same (spec, seed) must replay byte-identically
    replay = ArrivalProcess(spec, seed=seed)
    if process.fingerprint() != replay.fingerprint():
        _fail("arrival schedule is not replay-identical for one (spec, seed)")
    if [e.to_dict() for e in process.materialize()] != [
        e.to_dict() for e in replay.materialize()
    ]:
        _fail("fingerprints matched but materialised events differ")

    with tempfile.TemporaryDirectory(prefix="loadgen-smoke-") as tmp:
        ledger_path = os.path.join(tmp, "slo-ledger.jsonl")
        stack = await build_storm_stack(
            # undersized on purpose: the smoke should see real queueing,
            # not an idle system
            replicas=[
                SyntheticReplica("smoke-replica-0", concurrency=2,
                                 time_scale=0.2),
                SyntheticReplica("smoke-replica-1", concurrency=2,
                                 time_scale=0.2),
            ],
            time_scale=0.2,
            ledger_path=ledger_path,
        )
        report = await run_storm(stack, process, drain_s=20.0)
        stack.close()

        # gate 1: populated open-loop record, every arrival settled
        if report["arrivals"] <= 0:
            _fail("storm produced no arrivals")
        total = report["slo"]["total"]
        if total["admitted"] != report["arrivals"] - report["cancelled_at_drain"]:
            _fail(
                f"ledger admitted {total['admitted']} != "
                f"{report['arrivals']} arrivals - "
                f"{report['cancelled_at_drain']} cancelled"
            )
        if report["slo"]["pending"] != 0:
            _fail(f"{report['slo']['pending']} records leaked pending")
        if total["attainment"] is None:
            _fail("open_loop record has null attainment")
        if not report["slo"]["classes"]:
            _fail("no per-class rows in the SLO summary")

        # gate 2: zero torn ledger lines — every journaled line parses
        with open(ledger_path) as fh:
            raw_lines = [line for line in fh if line.strip()]
        parsed = 0
        for line in raw_lines:
            try:
                json.loads(line)
                parsed += 1
            except ValueError:
                _fail(f"torn ledger line: {line[:80]!r}")
        if parsed != total["admitted"]:
            _fail(f"journal has {parsed} lines, ledger settled {total['admitted']}")

    print(json.dumps({
        "arrivals": report["arrivals"],
        "offered_per_min": report["offered_per_min"],
        "achieved_per_min": report["achieved_per_min"],
        "attainment": total["attainment"],
        "shed": total["shed"],
        "deadline_exceeded": total["deadline_exceeded"],
        "goodput_analyses_per_min": total["goodput_analyses_per_min"],
        "fingerprint": report["fingerprint"][:16],
        "journal_lines": parsed,
    }, indent=2))
    print("loadgen smoke: OK")


if __name__ == "__main__":
    asyncio.run(_main())
