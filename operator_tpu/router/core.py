"""The failover router — health-gated, affinity-aware dispatch over N
serving replicas.

One :class:`EngineRouter` fronts a replica set (N ``ServingEngine``
deployments behind the headless Service, or any OpenAI-compatible
endpoints) and keeps analyses flowing through replica crashes, wedges,
and overload:

- **health gating** (``router/health.py``) — per-replica circuit
  breakers fed by passive error observations, plus probe/load verdicts:
  traffic drains off a sick replica before it hard-fails, and a breaker
  trip excludes it until a half-open probe succeeds;
- **placement** (``router/ring.py``) — consistent-hash affinity on the
  shared prompt prefix / incident fingerprint, so each replica's prefix
  cache, ``ResponseCache`` and incident-recall cache actually hit across
  the fleet; per-replica load reports (queue depth + the admission
  roofline's own per-token estimate) let the router SHED to a
  less-loaded healthy replica instead of rejecting — a request is
  refused only when no healthy replica exists at all;
- **failover** — a request in flight on a replica that dies or stalls is
  requeued at most ``max_failover`` times on a DIFFERENT replica with
  its residual absolute deadline (the budget keeps draining across the
  requeue, mirroring the supervisor's requeue discipline), the dead
  replica excluded; the idempotency key (a deterministic digest of the
  request) rides every attempt so at-least-once dispatch composes with
  the storage layer's idempotent status patches into exactly-once
  effects.

Counters (docs/METRICS.md): ``podmortem_router_routed_total``,
``podmortem_router_shed_total``, ``podmortem_router_failover_total``,
``podmortem_router_excluded_total``, ``podmortem_router_no_replica_total``.
Every attempt opens a ``router.dispatch`` span on the ambient analysis
trace (operator_tpu/obs/), so the flight recorder shows exactly which
replica served — or killed — each leg.

Chaos seam: set ``fault_plan`` (utils/faultinject.py) and every dispatch
attempt consults site ``router.dispatch`` with ``replica=<id>`` context —
replica kills and partitions inject there, deterministically.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import time
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable, Iterable, Optional

from ..obs import span as obs_span
from ..utils.timing import METRICS, MetricsRegistry
from .health import HealthBoard, ReplicaLoad
from .ring import HashRing

log = logging.getLogger(__name__)

__all__ = ["EngineRouter", "Replica", "RouteDecision", "RouteOutcome", "RouterError"]


@dataclass(frozen=True)
class Replica:
    """One routable serving replica: a stable identity plus (for HTTP
    replicas) its base URL."""

    id: str
    url: str = ""


@dataclass
class RouteDecision:
    """One placement: the chosen replica, whether load feedback shed it
    off the affinity owner, and who that owner was."""

    replica: Replica
    affinity_owner: str
    shed: bool = False


@dataclass
class RouteOutcome:
    """A completed dispatch: the backend's response plus the routing
    forensics the caller surfaces (AIResponse metadata, span attrs)."""

    response: Any
    replica_id: str
    attempts: int = 1
    requeues: int = 0
    shed: bool = False
    request_id: str = ""


class RouterError(Exception):
    """Dispatch exhausted: no healthy replica, or the failover budget is
    spent.  ``last_error`` carries the final replica failure (None when
    no attempt could even be placed)."""

    def __init__(self, message: str, *, last_error: Optional[BaseException] = None,
                 tried: Optional[list[str]] = None) -> None:
        super().__init__(message)
        self.last_error = last_error
        self.tried = list(tried or [])


def request_key(basis: str) -> str:
    """Deterministic idempotency key for one logical request — a digest,
    not a uuid, so a seeded chaos replay produces the identical key and
    the dispatch log replays byte-identically."""
    return hashlib.sha256(basis.encode()).hexdigest()[:16]


class EngineRouter:
    """Health-gated affinity router over a replica set (module doc)."""

    def __init__(
        self,
        replicas: Iterable["Replica | str"],
        *,
        vnodes: int = 64,
        shed_pressure: int = 8,
        failure_threshold: int = 3,
        reset_s: float = 10.0,
        max_failover: int = 1,
        clock: Optional[Callable[[], float]] = None,
        metrics: Optional[MetricsRegistry] = None,
    ) -> None:
        self._clock = clock or time.monotonic
        self.metrics = metrics or METRICS
        #: queue pressure (queued + inflight) past which the affinity
        #: owner is considered overloaded and load feedback may shed
        self.shed_pressure = max(1, shed_pressure)
        #: cross-replica requeues allowed per request (the supervisor's
        #: requeue-ONCE discipline, generalized)
        self.max_failover = max(0, max_failover)
        self.health = HealthBoard(
            failure_threshold=failure_threshold, reset_s=reset_s, clock=clock
        )
        self._replicas: dict[str, Replica] = {}
        self._ring = HashRing(vnodes=vnodes)
        for replica in replicas:
            self.add(replica)
        #: opt-in chaos seam (utils/faultinject.py), site "router.dispatch"
        self.fault_plan = None
        #: value-aware overload ladder (router/value.py OverloadPolicy):
        #: callers consult overload_verdict() BEFORE dispatching so the
        #: router can degrade or shed by value, not arrival order.
        #: None = pre-overload-control semantics (route() still sheds to
        #: a less-loaded replica, it just never drops work itself).
        self.policy = None

    # -- membership ----------------------------------------------------
    def add(self, replica: "Replica | str") -> None:
        if isinstance(replica, str):
            replica = Replica(id=replica)
        joined = replica.id not in self._replicas
        self._replicas[replica.id] = replica
        self._ring.add(replica.id)
        if joined:
            # counted only on a REAL membership change (idempotent re-adds
            # from a relist are silent) so ring_resize tracks actual remaps
            self.metrics.incr("ring_member_added")
            self.metrics.incr("ring_resize")

    def remove(self, replica_id: str) -> None:
        left = self._replicas.pop(replica_id, None) is not None
        self._ring.remove(replica_id)
        if left:
            # age the health/breaker/fabric-index state with the ring: a
            # departed replica's KV inventory must never match again (a
            # rejoin re-reports and starts clean)
            self.health.remove(replica_id)
            self.metrics.incr("ring_member_removed")
            self.metrics.incr("ring_resize")

    def replicas(self) -> list[Replica]:
        return [self._replicas[rid] for rid in sorted(self._replicas)]

    def __len__(self) -> int:
        return len(self._replicas)

    # -- feedback ------------------------------------------------------
    def report_load(self, replica_id: str, load: ReplicaLoad) -> None:
        """Ingest one replica's load report (a ``/healthz`` poll body or
        an in-process ``ServingEngine.load_report()``) — and refresh the
        fabric block index with the replica's URL so the fetch client
        can hit its /kv/blocks endpoint without a second lookup."""
        replica = self._replicas.get(replica_id)
        self.health.report_load(
            replica_id, load, url=replica.url if replica is not None else ""
        )

    def mark_probe(self, replica_id: str, ready: bool) -> None:
        self.health.for_replica(replica_id).mark_probe(ready)

    # -- placement -----------------------------------------------------
    @staticmethod
    def affinity_key(*, prefix: Optional[str] = None,
                     fingerprint: Optional[str] = None) -> str:
        """The placement key: the incident fingerprint when one exists
        (recurrences land where the recall cache is hot), else the
        prompt's shared prefix (the prefix cache's reuse unit), else ""
        (no affinity — pure load balancing)."""
        if fingerprint:
            return f"fp:{fingerprint}"
        if prefix:
            return f"px:{hashlib.sha256(prefix[:512].encode()).hexdigest()}"
        return ""

    def route(
        self,
        key: str = "",
        *,
        exclude: "frozenset[str] | set[str]" = frozenset(),
        deadline_s: Optional[float] = None,
        tokens: int = 256,
        kv_hint: Optional["list[str]"] = None,
        role: Optional[str] = None,
    ) -> Optional[RouteDecision]:
        """Pick one replica for a request.

        Health gate first (breaker + probe/gave-up state), then affinity
        (the ring walk from ``key``; keyless requests skip straight to
        least-loaded), then load feedback: an affinity owner whose queue
        pressure crosses ``shed_pressure`` — or whose roofline-queue
        estimate cannot fit the request inside ``deadline_s`` — sheds to
        the least-loaded healthy replica that CAN fit it (or the least
        loaded outright when nobody fits: degrade, never reject while
        any replica is healthy).  ``exclude`` removes replicas that
        already failed this request; the exclusion is waived when it
        would empty the healthy set (a single-replica set must still be
        retryable).  ``kv_hint`` (block-hash hexes from the prefix
        cache's hasher) re-ranks the candidates by how many of those
        blocks each replica's last KV inventory advertises — a failover
        lands on the survivor that can re-prefill from cache instead of
        recomputing; the inventory is advisory, so a zero-holder fleet
        falls back to plain affinity order.  ``role`` (fabric/disagg.py)
        partitions candidates by advertised replica role — exact match
        first, then mixed/unknown, then the opposite role — a stable
        PREFERENCE, never a filter: a fleet with no replica of the
        wanted role degrades to mixed rather than rejecting.  Returns
        None only when NO replica is healthy."""
        order = self._ring.preference(key) if key else sorted(self._replicas)
        # PURE filter: can_route never mutates breaker state — consuming
        # a recovering replica's half-open probe token here would let
        # traffic whose affinity lies elsewhere starve it of readmission;
        # dispatch() consumes admission (health.admit) for the one
        # replica it actually sends to
        healthy = [rid for rid in order if self.health.can_route(rid)]
        if not healthy:
            return None
        candidates = [rid for rid in healthy if rid not in exclude] or healthy
        if kv_hint:
            wanted = set(kv_hint)

            def held(rid: str) -> int:
                blocks = self.health.for_replica(rid).load.kv_blocks
                return len(wanted.intersection(blocks)) if blocks else 0

            # stable sort: block holders first (most blocks wins), the
            # affinity walk order breaks ties — no inventory anywhere
            # leaves the order untouched
            candidates = sorted(
                candidates,
                key=lambda rid: (-held(rid), candidates.index(rid)),
            )
        if role:
            from ..fabric.disagg import role_preference

            # stable partition AFTER the kv_hint re-rank so the role
            # tier dominates and inventory breaks ties within it: exact
            # role, then mixed/unknown, then the opposite role
            candidates = sorted(
                candidates,
                key=lambda rid: (
                    role_preference(
                        self.health.for_replica(rid).load.role, role
                    ),
                    candidates.index(rid),
                ),
            )
        owner = candidates[0]
        chosen = owner
        load = self.health.for_replica(owner).load
        overloaded = load.pressure() >= self.shed_pressure or (
            deadline_s is not None and load.est_wait_s(tokens) > deadline_s
        )
        if overloaded and len(candidates) > 1:
            def fits(rid: str) -> bool:
                candidate_load = self.health.for_replica(rid).load
                if candidate_load.pressure() >= self.shed_pressure:
                    return False
                return deadline_s is None or (
                    candidate_load.est_wait_s(tokens) <= deadline_s
                )

            # stable ordering: pressure first, affinity walk order as the
            # tie-break, so equal-load fleets keep their cache locality
            by_load = sorted(
                candidates,
                key=lambda rid: (self.health.for_replica(rid).load.pressure(),
                                 candidates.index(rid)),
            )
            chosen = next((rid for rid in by_load if fits(rid)), by_load[0])
        return RouteDecision(
            replica=self._replicas[chosen],
            affinity_owner=owner,
            shed=chosen != owner,
        )

    def fleet_pressure(self) -> Optional[float]:
        """The LEAST-loaded healthy replica's queue pressure — the best
        offer the fleet can make a new request.  None when no replica is
        healthy (route() would return None anyway)."""
        pressures = [
            self.health.for_replica(rid).load.pressure()
            for rid in self._replicas
            if self.health.can_route(rid)
        ]
        return min(pressures) if pressures else None

    def overload_verdict(
        self,
        *,
        value=None,
        request_id: str = "",
        site: str = "router",
    ):
        """Consult the value ladder (``self.policy``) for one request
        BEFORE dispatch: returns an ``OverloadVerdict`` (serve / degrade
        / shed) or None when no policy is wired, no value was scored, or
        no replica is healthy (the route itself will fail then — a shed
        verdict on top would misattribute the outcome)."""
        if self.policy is None or value is None:
            return None
        pressure = self.fleet_pressure()
        if pressure is None:
            return None
        verdict = self.policy.decide(
            value, pressure, site=site, request_id=request_id
        )
        if verdict.action == "shed":
            self.metrics.incr("router_value_shed")
        elif verdict.action == "degrade":
            self.metrics.incr("router_value_degraded")
        return verdict

    # -- dispatch ------------------------------------------------------
    async def dispatch(
        self,
        send: Callable[[Replica, int, Optional[float]], Awaitable[Any]],
        *,
        key: str = "",
        request_id: str = "",
        deadline: Optional[Any] = None,  # utils.deadline.Deadline
        attempts: int = 1,
        tokens: int = 256,
        backoff_s: float = 0.2,
        resume_log: Optional[Any] = None,  # router.resume.ResumeLog
        kv_hint: Optional["list[str]"] = None,
        role: Optional[str] = None,
    ) -> RouteOutcome:
        """Run ``send(replica, attempt, budget_s)`` against the routed
        replica, failing over across the set.

        ``deadline`` is the request's ABSOLUTE envelope: each attempt —
        including a cross-replica requeue — receives the RESIDUAL budget
        (``deadline.remaining()``), so queue time and dead-replica time
        already spent stay spent.  A replica failure feeds its breaker
        and excludes it; the request requeues on a different replica at
        most ``max_failover`` times (the supervisor's requeue-ONCE
        discipline), then the dispatch fails loudly.  Same-replica
        retries (single-replica sets) are bounded by ``attempts`` with
        exponential backoff and do not count as failovers.

        With ``resume_log`` (router/resume.py) the contract widens:
        ``send`` is called as ``send(replica, attempt, budget_s,
        resume_tokens)`` where ``resume_tokens`` is the generated-so-far
        checkpoint for ``request_id`` (None on the first attempt) — the
        replica re-prefills ``prompt + resume_tokens`` and decodes only
        the continuation, so a mid-stream replica death costs one
        re-prefill (mostly cached) instead of a full re-decode.  ``send``
        is responsible for checkpointing tokens as they stream; the
        router completes the log entry once the dispatch settles.
        ``kv_hint`` is forwarded to :meth:`route` on every attempt so a
        failover prefers survivors already holding the prompt's blocks.
        ``role`` (fabric/disagg.py) is forwarded the same way — a
        disaggregated leg keeps preferring its role across failovers,
        degrading to mixed replicas rather than failing.
        """
        tried: list[str] = []  # distinct replicas that failed, in order
        requeues = 0
        shed_any = False
        last_error: Optional[BaseException] = None
        for attempt in range(max(1, attempts)):
            budget = deadline.remaining() if deadline is not None else None
            if budget is not None and budget <= 0.0:
                raise RouterError(
                    f"deadline exhausted after {attempt} attempt(s)",
                    last_error=last_error, tried=tried,
                )
            decision = self.route(
                key, exclude=set(tried), deadline_s=budget, tokens=tokens,
                kv_hint=kv_hint, role=role,
            )
            if decision is None:
                self.metrics.incr("router_no_replica")
                raise RouterError(
                    "no healthy replica (all breakers open or probes failing)",
                    last_error=last_error, tried=tried,
                )
            replica = decision.replica
            if not self.health.admit(replica.id):
                # the consuming admission check lost a race for the
                # half-open probe token (another dispatch between this
                # task's route and now) — re-route on the next attempt
                continue
            if tried and replica.id not in tried:
                # moving to a replica that has not failed this request =
                # the cross-replica requeue; enforce the failover budget
                if requeues >= self.max_failover:
                    raise RouterError(
                        f"request failed after {requeues} cross-replica "
                        f"requeue(s) (tried {tried})",
                        last_error=last_error, tried=tried,
                    )
                requeues += 1
                self.metrics.incr("router_failover")
            shed_any = shed_any or decision.shed
            started = self._clock()
            try:
                with obs_span(
                    "router.dispatch",
                    replica=replica.id,
                    attempt=attempt,
                    shed=decision.shed,
                    requeue=requeues,
                    request=request_id,
                ):
                    if self.fault_plan is not None:
                        # apply_async: delay/jitter actions shape dispatch
                        # latency without blocking the loop
                        await self.fault_plan.apply_async(
                            "router.dispatch", replica=replica.id, attempt=attempt
                        )
                    if resume_log is not None:
                        call = send(
                            replica, attempt, budget,
                            resume_log.tokens(request_id),
                        )
                    else:
                        call = send(replica, attempt, budget)
                    result = await asyncio.wait_for(call, timeout=budget)
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # noqa: BLE001 - failures feed health; only
                # Exception — SystemExit/KeyboardInterrupt/MemoryError must
                # propagate, never read as replica weather
                last_error = exc
                if self.health.observe_failure(replica.id):
                    # this failure OPENED the breaker: the replica is now
                    # excluded from routing until its half-open probe
                    self.metrics.incr("router_excluded")
                if replica.id not in tried:
                    tried.append(replica.id)
                log.warning("router: replica %s attempt %d failed: %s",
                            replica.id, attempt + 1, exc)
                if len(tried) >= len(self._replicas):
                    # no FRESH replica left: the next attempt re-hammers
                    # an already-failed endpoint — back off (crash-looping
                    # replicas need the breathing room; the caller's
                    # deadline wait_for bounds the tail).  A failover to
                    # an untried sibling stays immediate instead.
                    await asyncio.sleep(min(2 ** attempt * backoff_s, 2.0))
                continue
            self.health.observe_success(replica.id, self._clock() - started)
            if resume_log is not None:
                # settled: drop the checkpoint (tombstones it in the
                # journal) — a replayed router must not resume a request
                # the client already received in full
                resume_log.complete(request_id)
            self.metrics.incr("router_routed")
            if decision.shed:
                self.metrics.incr("router_shed")
            return RouteOutcome(
                response=result,
                replica_id=replica.id,
                attempts=attempt + 1,
                requeues=requeues,
                shed=shed_any,
                request_id=request_id,
            )
        raise RouterError(
            f"dispatch failed after {max(1, attempts)} attempt(s) "
            f"(tried {tried})",
            last_error=last_error, tried=tried,
        )
