"""Value-aware overload control: shed-lowest-value-first, degrade-before-reject.

One value model for every point the stack can drop work (router shed,
scheduler queue eviction, admission clamp, supervisor requeue):

    value = f(SLO class, residual deadline, recall-hit probability)

DeepServe (arxiv 2501.14417) argues SLO-attainment signals must drive
admission, not just reporting; FailSafe (arxiv 2511.14116) argues resilient
serving degrades output quality before dropping requests.  Both disciplines
land here:

* **shed-lowest-value-first** — every shed site scores its candidates with
  the SAME model and drops the minimum-score request, so the router, the
  scheduler and the supervisor never disagree about who goes first;
* **degrade-before-reject** — above the shed line a ladder fires in order:
  truncate analysis depth (reduced ``max_tokens``, ``finish_reason:
  "degraded"``), then reject cold before recalled, and NEVER shed the SLO
  class already below its attainment target (fed live from
  ``obs/sloledger.py`` per-class attainment).

A recalled incident costs ~:data:`RECALL_COST_FRACTION` of a cold analysis
(memory/recall.py reuses the stored explanation), so the recall-hit
probability is an admission signal: a recalled request's expected cost is a
few percent of a cold one, which multiplies its value/cost score by ~25 —
structurally guaranteeing "recalled shed only after all cold requests of
equal-or-lower class" without a special case in the shed loop.

Everything in this module is pure and replay-deterministic (GL007): no
wall clocks, no ambient randomness — residual deadlines and queue pressure
are passed in by the caller, so the same seeded storm replays to a
byte-identical decision log.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, Iterable, List, Mapping, Optional, Tuple

__all__ = [
    "RECALL_COST_FRACTION",
    "RequestValue",
    "ValueModel",
    "OverloadPolicy",
    "OverloadVerdict",
    "ShedDecisionLog",
]

#: a recall hit replays a stored explanation instead of running a cold
#: analysis — measured at ~4% of the cold cost (prefill of the fingerprint
#: probe only), so expected cost = 1 - 0.96 * P(hit)
RECALL_COST_FRACTION = 0.04


@dataclass(frozen=True)
class RequestValue:
    """One request's scored admission value (pure data, no clocks).

    ``score`` is value per unit expected cost: class weight x deadline
    feasibility / expected cost fraction.  ``protected`` marks the request
    as belonging to an SLO class currently below its attainment target —
    the ladder never sheds those.
    """

    slo_class: str
    weight: float
    #: min(1, residual_s / target_s): 1.0 = whole budget left, 0.0 = spent.
    #: A request whose deadline is already blown has zero value — shedding
    #: it first is free goodput.
    feasibility: float
    recall_p: float
    protected: bool = False

    @property
    def expected_cost(self) -> float:
        return 1.0 - (1.0 - RECALL_COST_FRACTION) * self.recall_p

    @property
    def score(self) -> float:
        if self.feasibility <= 0.0:
            return 0.0
        return self.weight * self.feasibility / max(self.expected_cost, 1e-9)


class ValueModel:
    """Scores requests for the overload ladder.

    ``classes`` maps SLO class -> latency target seconds (the parsed
    ``slo_classes`` config).  Class weights are rank-based powers of 4 in
    order of tightening target (loosest first): with the default
    ``interactive:2,standard:30,batch:120`` that is batch=1, standard=4,
    interactive=16.  The spacing is chosen so a recalled request of class c
    (score ~ weight x 1/0.04 = 25x) always outranks EVERY cold request of
    class <= c, making "reject cold before recalled" fall out of plain
    min-score shedding.

    ``attainment`` is a live callable returning per-class attainment
    fractions (obs/sloledger.py ``attainment_by_class``); classes below
    ``attainment_target`` are protected from shedding.  When every known
    class is below target (total overload — someone must give), the class
    with the HIGHEST attainment loses its protection so the ladder cannot
    deadlock.
    """

    def __init__(
        self,
        classes: Mapping[str, float],
        *,
        attainment: Optional[Callable[[], Mapping[str, Optional[float]]]] = None,
        attainment_target: float = 0.9,
    ) -> None:
        self.classes: Dict[str, float] = {
            str(k): float(v) for k, v in classes.items()
        }
        self.attainment = attainment
        self.attainment_target = float(attainment_target)
        # loosest target first -> weight 4^rank; ties broken by name so the
        # ranking (and therefore every downstream shed decision) is stable
        # across replays regardless of dict insertion order
        ranked = sorted(
            self.classes.items(), key=lambda kv: (-kv[1], kv[0])
        )
        self.weights: Dict[str, float] = {
            name: float(4 ** rank) for rank, (name, _t) in enumerate(ranked)
        }

    def weight(self, slo_class: Optional[str]) -> float:
        if slo_class is None or slo_class not in self.weights:
            # unknown classes score as the loosest (cheapest to shed)
            return min(self.weights.values(), default=1.0)
        return self.weights[slo_class]

    def target_s(self, slo_class: Optional[str]) -> Optional[float]:
        if slo_class is None:
            return None
        return self.classes.get(slo_class)

    def protected_classes(self) -> "frozenset[str]":
        """Classes currently below their attainment target (never shed).

        Anti-deadlock waiver: when EVERY class with known attainment is
        below target and more than one is known, the best-attaining class
        is un-protected — total overload means someone must absorb the
        shed, and the least-behind class hurts least.
        """
        if self.attainment is None:
            return frozenset()
        att = self.attainment() or {}
        known = {
            c: a for c, a in att.items() if a is not None and c in self.classes
        }
        below = {c for c, a in known.items() if a < self.attainment_target}
        if below and len(known) > 1 and below == set(known):
            spare = max(below, key=lambda c: (known[c], c))
            below.discard(spare)
        return frozenset(below)

    def value(
        self,
        *,
        slo_class: Optional[str] = None,
        residual_s: Optional[float] = None,
        recall_p: float = 0.0,
        protected: Optional[bool] = None,
    ) -> RequestValue:
        """Score one request.  ``residual_s`` is the remaining deadline
        budget in seconds (None = no deadline -> feasibility 1.0); the
        caller derives it from ITS clock so this stays wall-clock-free."""
        cls = slo_class or "default"
        target = self.target_s(slo_class)
        if residual_s is None or target is None or target <= 0:
            feasibility = 1.0
        else:
            feasibility = min(1.0, max(0.0, residual_s / target))
        if protected is None:
            protected = cls in self.protected_classes()
        return RequestValue(
            slo_class=cls,
            weight=self.weight(slo_class),
            feasibility=feasibility,
            recall_p=max(0.0, min(1.0, float(recall_p))),
            protected=bool(protected),
        )


class ShedDecisionLog:
    """Bounded, byte-comparable record of every shed/degrade decision.

    Lines are canonical (fixed field order, rounded scores) so two replays
    of the same seeded storm compare with ``==`` on :meth:`text` — the
    GL007 determinism proof surface.  Bounded at ``cap`` lines with a
    dropped-counter so a runaway storm cannot eat the heap.
    """

    def __init__(self, cap: int = 4096) -> None:
        self.cap = int(cap)
        self._lines: List[str] = []
        self.dropped = 0

    def record(
        self,
        *,
        site: str,
        request_id: str,
        value: RequestValue,
        action: str,
        reason: str,
        cutoff: float,
    ) -> None:
        line = (
            f"site={site} id={request_id} cls={value.slo_class} "
            f"action={action} reason={reason} "
            f"score={round(value.score, 6)} cutoff={round(cutoff, 6)} "
            f"recalled={1 if value.recall_p > 0.5 else 0} "
            f"protected={1 if value.protected else 0}"
        )
        if len(self._lines) >= self.cap:
            self.dropped += 1
            return
        self._lines.append(line)

    def lines(self) -> List[str]:
        return list(self._lines)

    def text(self) -> str:
        return "\n".join(self._lines) + ("\n" if self._lines else "")

    def clear(self) -> None:
        self._lines.clear()
        self.dropped = 0


@dataclass(frozen=True)
class OverloadVerdict:
    """What the ladder says to do with one request at one site."""

    action: str  # "serve" | "degrade" | "shed"
    reason: str
    value: RequestValue
    cutoff: float
    #: fraction of the original max_tokens a degraded request keeps
    degrade_tokens_frac: float = 1.0


class OverloadPolicy:
    """The degradation ladder, shared by every shed site.

    Pressure is the caller's unitless load signal (router: queue depth +
    inflight per replica; scheduler: queued + running rows).  The ladder:

    * ``pressure < degrade_pressure`` — serve untouched;
    * ``degrade_pressure <= pressure < shed_pressure`` — DEGRADE: serve
      with ``max_tokens`` scaled by ``degrade_tokens_frac`` (truncate
      analysis depth before rejecting anything);
    * ``pressure >= shed_pressure`` — SHED the request iff its score falls
      below ``cutoff = shed_value_floor * pressure / shed_pressure`` (the
      bar rises with overload) AND its class is not protected; protected
      or above-cutoff requests are degraded instead, never dropped.

    Decisions are appended to :attr:`log` and counted into ``metrics``
    (``shed{reason,slo_class}`` / ``degraded{slo_class}`` labeled
    counters) — the observability surface docs/METRICS.md documents.
    """

    def __init__(
        self,
        model: ValueModel,
        *,
        shed_pressure: float = 8.0,
        degrade_pressure: Optional[float] = None,
        degrade_tokens_frac: float = 0.25,
        shed_value_floor: float = 1.0,
        metrics=None,
        log: Optional[ShedDecisionLog] = None,
    ) -> None:
        self.model = model
        self.shed_pressure = max(1.0, float(shed_pressure))
        if degrade_pressure is None:
            degrade_pressure = max(1.0, self.shed_pressure / 2.0)
        self.degrade_pressure = max(1.0, float(degrade_pressure))
        self.degrade_tokens_frac = float(degrade_tokens_frac)
        self.shed_value_floor = float(shed_value_floor)
        self.metrics = metrics
        self.log = log if log is not None else ShedDecisionLog()

    def cutoff(self, pressure: float) -> float:
        """The shed bar at this pressure: rises linearly past the shed
        line, so deeper overload sheds progressively higher-value work
        (smooth decay, not a cliff)."""
        return self.shed_value_floor * (float(pressure) / self.shed_pressure)

    def decide(
        self,
        value: RequestValue,
        pressure: float,
        *,
        site: str = "router",
        request_id: str = "",
    ) -> OverloadVerdict:
        pressure = float(pressure)
        cutoff = self.cutoff(pressure)
        if pressure < self.degrade_pressure:
            return OverloadVerdict(
                action="serve", reason="under-pressure", value=value,
                cutoff=cutoff,
            )
        if pressure < self.shed_pressure:
            verdict = OverloadVerdict(
                action="degrade", reason="pressure-band", value=value,
                cutoff=cutoff, degrade_tokens_frac=self.degrade_tokens_frac,
            )
        elif value.protected:
            verdict = OverloadVerdict(
                action="degrade", reason="class-protected", value=value,
                cutoff=cutoff, degrade_tokens_frac=self.degrade_tokens_frac,
            )
        elif value.score >= cutoff:
            verdict = OverloadVerdict(
                action="degrade", reason="above-cutoff", value=value,
                cutoff=cutoff, degrade_tokens_frac=self.degrade_tokens_frac,
            )
        else:
            verdict = OverloadVerdict(
                action="shed", reason="below-cutoff", value=value,
                cutoff=cutoff,
            )
        self._account(verdict, site=site, request_id=request_id)
        return verdict

    def pick_eviction(
        self, candidates: Iterable[Tuple[str, RequestValue]]
    ) -> Optional[Tuple[str, RequestValue]]:
        """Lowest-score non-protected candidate, or None when every
        candidate is protected (the queue must grow instead).  Ties break
        on the id so replayed storms evict the same victim."""
        best: Optional[Tuple[str, RequestValue]] = None
        for rid, value in candidates:
            if value.protected:
                continue
            if best is None or (value.score, rid) < (best[1].score, best[0]):
                best = (rid, value)
        return best

    def record_eviction(
        self, rid: str, value: RequestValue, *, pressure: float,
        site: str = "sched",
    ) -> None:
        verdict = OverloadVerdict(
            action="shed", reason="queue-evict", value=value,
            cutoff=self.cutoff(pressure),
        )
        self._account(verdict, site=site, request_id=rid)

    def _account(
        self, verdict: OverloadVerdict, *, site: str, request_id: str
    ) -> None:
        self.log.record(
            site=site, request_id=request_id, value=verdict.value,
            action=verdict.action, reason=verdict.reason,
            cutoff=verdict.cutoff,
        )
        if self.metrics is None:
            return
        cls = verdict.value.slo_class
        if verdict.action == "shed":
            self.metrics.incr(
                "shed", labels={"reason": verdict.reason, "slo_class": cls}
            )
        elif verdict.action == "degrade":
            self.metrics.incr("degraded", labels={"slo_class": cls})
