"""Endpoint-watch fleet membership (docs/SCALING.md).

The serving fleet stops being a static URL list: :class:`EndpointDiscovery`
lists + watches the headless serving Service's ``Endpoints`` object and
mutates an :class:`~operator_tpu.router.EngineRouter`'s consistent-hash
ring live —

- a pod that turns Ready appears in ``subsets[].addresses`` and JOINS:
  optionally pre-warmed first (an async health probe that also primes the
  replica's load/KV view) so it never takes traffic before it can serve;
- a pod that dies or goes NotReady disappears and LEAVES: the ring drops
  its vnodes (only ~1/N of keys remap — consistent hashing), and any
  in-flight request on it drains through the router's existing
  breaker/failover path;
- the watch resumes from the list's ``resourceVersion`` via the shared
  :func:`~operator_tpu.operator.kubeapi.iter_watch_resumed` discipline —
  a 410 compaction triggers a relist, a plain close resumes at the
  cursor, and every apiserver call outside the watch stream itself is
  bounded by ``kube_timeout_s`` (graftlint GL003).

Membership changes emit ``podmortem_ring_member_added_total`` /
``podmortem_ring_member_removed_total`` / ``podmortem_ring_resize_total``
(from the router itself, so storm-harness membership counts too).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Awaitable, Callable, Optional

from ..operator.kubeapi import KubeApi, WatchExpired, iter_watch_resumed
from .core import EngineRouter, Replica

log = logging.getLogger(__name__)

__all__ = ["EndpointDiscovery", "endpoint_urls"]


def endpoint_urls(
    obj: dict, *, scheme: str = "http", port_name: str = "http"
) -> dict[str, str]:
    """READY replica URLs from a raw Endpoints dict: ``{replica_id: url}``.

    Each subset contributes its ready ``addresses`` crossed with ONE port —
    the one named ``port_name``, else the subset's first port (a
    single-port serving Service needs no name).  NotReady addresses are
    deliberately excluded: the kubelet's readiness gate is the first
    admission filter, the pre-warm probe the second.  The replica id IS
    the URL, so the consistent-hash ring keys on a stable identity that
    survives operator restarts.
    """
    urls: dict[str, str] = {}
    for subset in obj.get("subsets") or []:
        ports = subset.get("ports") or []
        port = None
        for p in ports:
            if p.get("name") == port_name:
                port = p.get("port")
                break
        if port is None and ports:
            port = ports[0].get("port")
        if port is None:
            continue
        for addr in subset.get("addresses") or []:
            ip = addr.get("ip")
            if not ip:
                continue
            host = f"[{ip}]" if ":" in ip else ip
            url = f"{scheme}://{host}:{port}"
            urls[url] = url
    return urls


class EndpointDiscovery:
    """Drive one router's membership from one Service's Endpoints."""

    def __init__(
        self,
        api: KubeApi,
        router: EngineRouter,
        *,
        service: str,
        namespace: str = "default",
        scheme: str = "http",
        port_name: str = "http",
        kube_timeout_s: float = 15.0,
        restart_delay_s: float = 5.0,
        prewarm: Optional[Callable[[Replica], Awaitable[bool]]] = None,
    ) -> None:
        self.api = api
        self.router = router
        self.service = service
        self.namespace = namespace
        self.scheme = scheme
        self.port_name = port_name
        #: budget for each relist (graftlint GL003; mirrors
        #: OperatorConfig.kube_call_timeout_s)
        self.kube_timeout_s = kube_timeout_s
        self.restart_delay_s = restart_delay_s
        #: async gate a joining replica must pass before ring insertion
        #: (providers.OpenAICompatProvider.prewarm_replica: a /healthz
        #: probe whose load report also primes the health board); a False
        #: or raising pre-warm SKIPS the join — the next Endpoints event
        #: or relist retries it
        self.prewarm = prewarm
        #: replica ids this loop added (never remove members someone else
        #: placed in the router, e.g. a static seed set)
        self._managed: set[str] = set()
        self._cursor: Optional[str] = None
        self._synced = asyncio.Event()

    # -- introspection -------------------------------------------------
    def members(self) -> list[str]:
        return sorted(self._managed)

    async def wait_synced(self, timeout_s: float) -> bool:
        """Best-effort wait for the first successful list+sync."""
        try:
            await asyncio.wait_for(self._synced.wait(), timeout_s)
            return True
        except asyncio.TimeoutError:
            return False

    # -- sync ----------------------------------------------------------
    async def _sync(self, obj: Optional[dict]) -> None:
        """Reconcile the router against one Endpoints snapshot (None =
        the object is gone: drain every managed member)."""
        urls = (
            endpoint_urls(obj, scheme=self.scheme, port_name=self.port_name)
            if obj is not None
            else {}
        )
        desired = set(urls)
        for replica_id in sorted(self._managed - desired):
            self._managed.discard(replica_id)
            self.router.remove(replica_id)
            log.info("discovery: %s left the serving fleet (drained via "
                     "breaker/failover)", replica_id)
        for replica_id in sorted(desired - self._managed):
            replica = Replica(id=replica_id, url=urls[replica_id])
            if self.prewarm is not None:
                try:
                    ready = await self.prewarm(replica)
                except asyncio.CancelledError:
                    raise
                except Exception as exc:  # noqa: BLE001 - a bad probe just defers the join
                    log.warning("discovery: pre-warm probe for %s failed "
                                "(%s); join deferred", replica_id, exc)
                    continue
                if not ready:
                    log.info("discovery: %s not ready yet; join deferred",
                             replica_id)
                    continue
                if replica_id in self._managed:
                    # revalidate after the probe await: a concurrent sync
                    # (watch event racing the relist) already admitted it —
                    # adding again would double-register with the router
                    continue
            self._managed.add(replica_id)
            self.router.add(replica)
            log.info("discovery: %s joined the serving fleet (pre-warmed, "
                     "~1/N keys remap)", replica_id)

    def _is_ours(self, raw: dict) -> bool:
        meta = raw.get("metadata") or {}
        return (
            meta.get("name") == self.service
            and meta.get("namespace") == self.namespace
        )

    async def _relist(self) -> None:
        items, cursor = await asyncio.wait_for(
            self.api.list_rv("Endpoints", self.namespace),
            timeout=self.kube_timeout_s,
        )
        ours = next((raw for raw in items if self._is_ours(raw)), None)
        await self._sync(ours)
        self._cursor = cursor
        self._synced.set()

    # -- loop ----------------------------------------------------------
    async def run(self, stop: asyncio.Event) -> None:
        """Maintain membership until ``stop``: list, then watch-resumed;
        relist on 410, resume (or relist when the cursor died with the
        stream) on any other interruption."""
        def set_cursor(value: Optional[str]) -> None:
            self._cursor = value

        primed = False
        while not stop.is_set():
            try:
                if not primed or self._cursor is None:
                    await self._relist()
                    primed = True
                async for event, version in iter_watch_resumed(
                    self.api, "Endpoints", self.namespace,
                    lambda: self._cursor, set_cursor,
                ):
                    if self._is_ours(event.object):
                        await self._sync(
                            None if event.type == "DELETED" else event.object
                        )
                    if version:
                        # graftlint: disable=GL011 reason=cursor advance is single-writer (one run() task per discovery); monotonic resourceVersion overwrite is the informer discipline
                        self._cursor = version
                    if stop.is_set():
                        return
            except asyncio.CancelledError:
                raise
            except WatchExpired:
                # the helper already cleared the cursor; only a fresh
                # LIST restores a consistent membership view
                log.warning("discovery: Endpoints cursor expired; re-listing")
                primed = False
                await _interruptible_sleep(stop, self.restart_delay_s)
            except Exception:  # noqa: BLE001 - WatchClosed, ApiError from relist, ...
                log.warning("discovery: membership watch interrupted; "
                            "resyncing", exc_info=True)
                await _interruptible_sleep(stop, self.restart_delay_s)


async def _interruptible_sleep(stop: asyncio.Event, delay_s: float) -> None:
    try:
        await asyncio.wait_for(stop.wait(), timeout=delay_s)
    except asyncio.TimeoutError:
        pass
