"""Token-level streaming resume checkpoints for the router.

A replica death mid-stream used to mean restart-from-scratch: the router
re-dispatched the request and the survivor re-prefilled the prompt and
re-decoded every token the client had already been streamed.  The
:class:`ResumeLog` closes the second half of that waste: as tokens
stream back, the router checkpoints the generated-so-far ids per
request; on failover it hands the survivor ``prompt + generated`` as
the resume point, so the survivor re-prefills (cheap, and mostly cached
when the prefix store holds the blocks — serving/kvstore.py) instead of
re-DECODING (expensive, one step per token).  The client's stream then
strictly extends: no token is ever re-emitted, because the scheduler
bills the resumed tokens as prompt and emits only the continuation.

Durability rides :class:`operator_tpu.utils.journal.Journal` — the same
torn-line-tolerant append-only JSONL as the incident store and claim
ledger, so a router crash loses at most the final checkpoint line (the
resume point degrades by one flush interval, never corrupts).  Records
are last-wins per request id; ``done`` tombstones drop completed
requests at replay.  ``path=None`` keeps the log purely in memory —
resume still works across replica deaths within one router process,
which is the common case (tests/test_kv_economy.py drives it this way).

Thread-safety: the router's dispatch path is single-event-loop, and the
Journal serializes its own IO; no extra lock is needed here.
"""

from __future__ import annotations

from typing import Optional

from ..utils.journal import Journal

__all__ = ["ResumeLog"]


class ResumeLog:
    """Per-request generated-token checkpoints with journal durability.

    Monotonic contract: :meth:`checkpoint` only ever EXTENDS a request's
    recorded tokens — a shorter (stale, out-of-order) report is dropped,
    so a resume point can never move backwards and a replayed journal
    reduces to the longest checkpoint per request.
    """

    def __init__(self, path: Optional[str] = None, *,
                 compact_every: int = 256) -> None:
        self._tokens: dict[str, list[int]] = {}
        self._compact_every = max(1, compact_every)
        # async_writes: checkpoint/complete run on the router's dispatch
        # path — appends must enqueue to the writer thread, not do file
        # IO on the event loop (graftlint GL006)
        self._journal = Journal(path, label="resume-log", async_writes=True)
        self._journal.load(self._replay)
        self._journal.open()

    def _replay(self, record: dict) -> None:
        request_id = str(record["id"])
        if record.get("done"):
            self._tokens.pop(request_id, None)
            return
        tokens = record.get("tokens")
        if not isinstance(tokens, list):
            raise ValueError("resume record without tokens")
        current = self._tokens.get(request_id)
        # last-wins, but keep the monotonic guarantee against reordered
        # or duplicated lines: never replace a checkpoint with a shorter one
        if current is None or len(tokens) > len(current):
            self._tokens[request_id] = [int(t) for t in tokens]

    # -- recording -----------------------------------------------------
    def checkpoint(self, request_id: str, token_ids: "list[int]") -> bool:
        """Record the generated-so-far ids for ``request_id``.  Returns
        False (and writes nothing) unless this strictly extends the
        previous checkpoint."""
        current = self._tokens.get(request_id)
        if current is not None and len(token_ids) <= len(current):
            return False
        tokens = [int(t) for t in token_ids]
        self._tokens[request_id] = tokens
        self._journal.append({"id": request_id, "tokens": tokens})
        self._maybe_compact()
        return True

    def complete(self, request_id: str) -> None:
        """The request settled (success or terminal failure): drop its
        checkpoint and tombstone it in the journal so replay forgets it."""
        if self._tokens.pop(request_id, None) is not None:
            self._journal.append({"id": request_id, "done": True})
            self._maybe_compact()

    # -- reads ---------------------------------------------------------
    def tokens(self, request_id: str) -> Optional["list[int]"]:
        """Generated-so-far ids for a live request (a copy), or None."""
        current = self._tokens.get(request_id)
        return list(current) if current is not None else None

    def __len__(self) -> int:
        return len(self._tokens)

    def close(self) -> None:
        self._journal.close()

    # -- compaction ----------------------------------------------------
    def _maybe_compact(self) -> None:
        """Every checkpoint rewrites the request's full token list, so an
        L-token stream costs O(L) lines of O(L) tokens — compact once the
        journal is clearly dominated by superseded lines."""
        if self._journal.lines > max(self._compact_every,
                                     2 * len(self._tokens)):
            self._journal.compact([
                {"id": request_id, "tokens": tokens}
                for request_id, tokens in self._tokens.items()
            ])
