"""Health gating for the multi-replica data plane.

Two layers, composed by :class:`HealthBoard`:

- **circuit breakers** — :class:`CircuitBreaker` / :class:`BreakerBoard`
  moved here from ``operator/providers.py`` (which re-exports them
  unchanged): the consecutive-failure state machine that turns a dying
  backend from "every call burns a deadline budget" into "calls skip it
  until a half-open probe succeeds".  The board is keyed generically
  (:meth:`BreakerBoard.for_key`) so one mechanism serves both the
  per-provider breakers the pipeline has had since PR 1 and the
  per-REPLICA breakers the router adds — a sick replica drains before it
  hard-fails, while its siblings keep serving.
- **passive scoring + load reports** — :class:`ReplicaHealth` keeps an
  EWMA of observed latency, a consecutive-error count, an optional
  probe verdict (``/healthz`` polls or an injected check), and the
  replica's last :class:`ReplicaLoad` report (queue depth + roofline
  decode estimate from ``ServingEngine.load_report``).  The router's
  shed decision reads these; nothing here blocks.

The clock is injectable end to end so chaos tests drive every state
machine deterministically (tests/test_router.py).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Optional

__all__ = [
    "CircuitBreaker",
    "BreakerBoard",
    "ReplicaHealth",
    "ReplicaLoad",
    "HealthBoard",
    "fleet_rollup",
]


def fleet_rollup(replicas: dict) -> dict:
    """Aggregate per-replica fleet rows (``HealthBoard.fleet_view``
    shape) into one fleet summary.  MFU / occupancy / host-gap means are
    STEP-WEIGHTED over the replicas that reported them — a replica with
    an empty step ring contributes nothing, not a zero; queue depth and
    inflight are plain sums.  Module-level so the operator can merge
    rows across several routed replica sets before rolling up."""
    mfu_w = gap_w = occ_w = 0.0
    mfu_steps = gap_steps = occ_steps = 0
    queue_depth = inflight = 0
    # SLO attainment is weighted by each replica's settled-request count
    # (a replica that served 10x the traffic moves the fleet number 10x
    # as much); goodput is a plain sum — tokens/s add across replicas
    slo_w = 0.0
    slo_requests = 0
    goodput = 0.0
    goodput_seen = False
    # KV economy: pages sum across replicas; the fleet hit rate is
    # weighted by each replica's lookup count (a replica that answered
    # 10x the block lookups moves the fleet number 10x as much)
    kv_free = kv_total = 0
    hit_w = 0.0
    hit_lookups = 0
    # overload-ladder totals (router/value.py): plain sums — shed and
    # degraded counts add across replicas
    shed = degraded = 0
    # disaggregation (fabric/disagg.py): per-role replica counts and
    # queue pressure so the autoscaler can see ONE starved role behind a
    # calm aggregate (all prefill replicas saturated, decode idle)
    roles: dict = {}
    for row in replicas.values():
        queue_depth += int(row.get("queueDepth") or 0)
        inflight += int(row.get("inflight") or 0)
        role = str(row.get("role") or "mixed")
        tier = roles.setdefault(
            role, {"replicas": 0, "ready": 0, "pressure": 0}
        )
        tier["replicas"] += 1
        tier["ready"] += 1 if row.get("ready") else 0
        tier["pressure"] += int(row.get("queueDepth") or 0) + int(
            row.get("inflight") or 0
        )
        shed += int(row.get("shedTotal") or 0)
        degraded += int(row.get("degradedTotal") or 0)
        weight = max(1, int(row.get("steps") or 0))
        if row.get("decodeMfu") is not None:
            mfu_w += float(row["decodeMfu"]) * weight
            mfu_steps += weight
        if row.get("hostGapFrac") is not None:
            gap_w += float(row["hostGapFrac"]) * weight
            gap_steps += weight
        if row.get("occupancy") is not None:
            occ_w += float(row["occupancy"]) * weight
            occ_steps += weight
        if row.get("sloAttainment") is not None:
            slo_weight = max(1, int(row.get("sloCompleted") or 0))
            slo_w += float(row["sloAttainment"]) * slo_weight
            slo_requests += slo_weight
        if row.get("goodput") is not None:
            goodput += float(row["goodput"])
            goodput_seen = True
        kv_free += int(row.get("kvPagesFree") or 0)
        kv_total += int(row.get("kvPagesTotal") or 0)
        if row.get("prefixHitRate") is not None:
            weight = max(1, int(row.get("kvLookups") or 0))
            hit_w += float(row["prefixHitRate"]) * weight
            hit_lookups += weight
    return {
        "replicaCount": len(replicas),
        "readyCount": sum(1 for r in replicas.values() if r.get("ready")),
        "queueDepth": queue_depth,
        "inflight": inflight,
        "decodeMfu": round(mfu_w / mfu_steps, 6) if mfu_steps else None,
        "hostGapFrac": round(gap_w / gap_steps, 6) if gap_steps else None,
        "occupancy": round(occ_w / occ_steps, 6) if occ_steps else None,
        "sloAttainment": round(slo_w / slo_requests, 6) if slo_requests else None,
        "goodput": round(goodput, 6) if goodput_seen else None,
        "kvPagesFree": kv_free,
        "kvPagesTotal": kv_total,
        "prefixHitRate": (
            round(hit_w / hit_lookups, 6) if hit_lookups else None
        ),
        "shedTotal": shed,
        "degradedTotal": degraded,
        "roles": {role: roles[role] for role in sorted(roles)},
    }


class CircuitBreaker:
    """Consecutive-failure breaker for one backend (provider or replica).

    States: ``closed`` (calls flow) → after ``failure_threshold``
    consecutive failures ``open`` (calls skipped: a dead backend must stop
    burning the deadline budget — the pipeline falls through the existing
    degradation ladder and stores pattern-only results) → after
    ``reset_s`` ``half-open`` (exactly ONE probe flows) → probe success
    closes, probe failure re-opens for another window.

    The clock is injectable so chaos tests drive the state machine
    deterministically (tests/test_chaos.py).
    """

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half-open"

    def __init__(
        self,
        failure_threshold: int = 5,
        reset_s: float = 30.0,
        clock: Optional[Callable[[], float]] = None,
    ) -> None:
        self.failure_threshold = max(1, failure_threshold)
        self.reset_s = reset_s
        self._clock = clock or time.monotonic
        self.state = self.CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_at = 0.0

    def allow(self) -> bool:
        """May a call be attempted now?  Transitions open → half-open when
        the reset window elapsed (that caller IS the probe; concurrent
        callers in half-open are refused until the probe resolves).  A
        probe whose caller died without ever reporting (cancelled task,
        operator shutdown mid-call) must not wedge the breaker: after
        another full window in half-open a fresh probe is admitted."""
        now = self._clock()
        if self.state == self.OPEN:
            if now - self._opened_at >= self.reset_s:
                self.state = self.HALF_OPEN
                self._probe_at = now
                return True
            return False
        if self.state == self.HALF_OPEN:
            if now - self._probe_at >= self.reset_s:
                self._probe_at = now
                return True
            return False
        return True

    def can_attempt(self) -> bool:
        """PURE read: would :meth:`allow` admit a call now?  No state
        transition and no probe-token consumption — the router's health
        FILTER asks this about every replica on every route; only the
        caller actually about to dispatch consumes via ``allow()``
        (otherwise routing traffic whose affinity lies elsewhere would
        burn a recovering replica's single half-open probe and starve it
        of readmission)."""
        now = self._clock()
        if self.state == self.OPEN:
            return now - self._opened_at >= self.reset_s
        if self.state == self.HALF_OPEN:
            return now - self._probe_at >= self.reset_s
        return True

    def record_success(self) -> None:
        self._consecutive_failures = 0
        self.state = self.CLOSED

    def record_failure(self) -> bool:
        """Returns True when THIS failure opened (or re-opened) the
        breaker — the caller's cue to count/emit the trip once."""
        if self.state == self.HALF_OPEN:
            self.state = self.OPEN
            self._opened_at = self._clock()
            return True
        self._consecutive_failures += 1
        if (
            self.state == self.CLOSED
            and self._consecutive_failures >= self.failure_threshold
        ):
            self.state = self.OPEN
            self._opened_at = self._clock()
            return True
        return False


class BreakerBoard:
    """One CircuitBreaker per key, created on first use.  Keys are
    provider ids on the pipeline's board and replica ids on the
    router's — same machinery, different granularity."""

    def __init__(
        self,
        failure_threshold: int = 5,
        reset_s: float = 30.0,
        clock: Optional[Callable[[], float]] = None,
    ) -> None:
        self.failure_threshold = failure_threshold
        self.reset_s = reset_s
        self._clock = clock
        self._breakers: dict[str, CircuitBreaker] = {}

    def remove(self, key: str) -> None:
        """Drop a key's breaker outright (replica left the ring); a
        rejoin under the same id starts closed, like any new replica."""
        self._breakers.pop(key, None)

    def for_key(self, key: str) -> CircuitBreaker:
        breaker = self._breakers.get(key)
        if breaker is None:
            breaker = CircuitBreaker(
                self.failure_threshold, self.reset_s, clock=self._clock
            )
            self._breakers[key] = breaker
        return breaker

    def for_provider(self, provider_id: Optional[str]) -> CircuitBreaker:
        """The pipeline's historical entry point (None → "template")."""
        return self.for_key(provider_id or "template")

    def states(self) -> dict[str, str]:
        return {key: b.state for key, b in self._breakers.items()}


@dataclass
class ReplicaLoad:
    """One replica's self-reported load — the feedback the shed decision
    reads.  Produced by ``ServingEngine.load_report()`` and carried on
    ``GET /healthz`` (serving/httpserver.py); all fields degrade to
    "unknown = no pressure" so a replica that never reported is routable.
    """

    #: requests queued ahead of admission (ServingEngine._queue)
    queue_depth: int = 0
    #: admitted + popped-but-unadmitted requests riding the engine now
    inflight: int = 0
    #: measured/roofline seconds per decoded token (0.0 = unknown) — the
    #: admission roofline's own estimate, so the router's residual-fit
    #: check agrees with what the replica itself would clamp to
    decode_token_s: float = 0.0
    #: the engine's supervisor exhausted its reset budget (serving cold
    #: until the window drains) — treated as not-ready
    gave_up: bool = False
    #: step-clock perf summary (serving/perf.py): measured attributed
    #: decode MFU over the replica's step ring, the host-gap stall
    #: fraction, mean slot occupancy, and how many step records back
    #: them.  None/0 = replica predates the step clock or has not
    #: decoded yet — the fleet view skips it, routing is unaffected.
    decode_mfu: Optional[float] = None
    host_gap_frac: Optional[float] = None
    occupancy: Optional[float] = None
    steps: int = 0
    #: per-class SLO aggregates (obs/sloledger.py SLOBoard via
    #: ``ServingEngine.load_report()``): fraction of settled requests
    #: that attained their SLO, goodput-under-SLO tokens/s, how many
    #: settled requests back the fraction, and the per-class breakdown.
    #: None = replica predates the board or has settled nothing.
    slo_attainment: Optional[float] = None
    goodput_tokens_s: Optional[float] = None
    slo_completed: int = 0
    slo_classes: Optional[dict] = None
    #: KV economy (serving/kvstore.py via ``ServingEngine.load_report``):
    #: free/total device KV pages, the prefix cache's lifetime hit rate
    #: over ``prefix_lookups`` block lookups (None = caching off or the
    #: replica predates it), and a bounded MRU inventory of block hashes
    #: (hex) the replica holds — the peer index a failover consults to
    #: prefer a survivor that already has the prompt's blocks resident.
    kv_pages_free: int = 0
    kv_pages_total: int = 0
    prefix_hit_rate: Optional[float] = None
    prefix_lookups: int = 0
    kv_blocks: Optional[list] = None
    #: prefill/decode disaggregation role (fabric/disagg.py): "prefill",
    #: "decode", or "mixed".  A routing PREFERENCE, never a filter —
    #: unknown/legacy replicas read as mixed and serve everything.
    role: str = "mixed"
    #: value-aware overload ladder totals (router/value.py): requests
    #: this replica shed (dropped by value) and served degraded
    #: (depth-truncated) — rolled up fleet-wide by ``fleet_rollup``
    shed: int = 0
    degraded: int = 0

    def pressure(self) -> int:
        """Scalar queue pressure used for least-loaded comparison."""
        return self.queue_depth + self.inflight

    def est_wait_s(self, tokens: int) -> float:
        """Crude roofline-queue estimate of seconds until a NEW request
        of ``tokens`` decode tokens completes here: everything already
        riding the engine plus this request, at the replica's own
        per-token estimate.  0.0 when the rate is unknown."""
        if self.decode_token_s <= 0.0:
            return 0.0
        return self.decode_token_s * tokens * (1 + self.pressure())

    def to_dict(self) -> dict:
        return {
            "queueDepth": self.queue_depth,
            "inflight": self.inflight,
            "decodeTokenS": round(self.decode_token_s, 6),
            "gaveUp": self.gave_up,
            "decodeMfu": (
                round(self.decode_mfu, 6) if self.decode_mfu is not None
                else None
            ),
            "hostGapFrac": (
                round(self.host_gap_frac, 6)
                if self.host_gap_frac is not None else None
            ),
            "occupancy": (
                round(self.occupancy, 6) if self.occupancy is not None
                else None
            ),
            "steps": self.steps,
            "sloAttainment": (
                round(self.slo_attainment, 6)
                if self.slo_attainment is not None else None
            ),
            "goodput": (
                round(self.goodput_tokens_s, 6)
                if self.goodput_tokens_s is not None else None
            ),
            "sloCompleted": self.slo_completed,
            "sloClasses": self.slo_classes,
            "kvPagesFree": self.kv_pages_free,
            "kvPagesTotal": self.kv_pages_total,
            "prefixHitRate": (
                round(self.prefix_hit_rate, 6)
                if self.prefix_hit_rate is not None else None
            ),
            "kvLookups": self.prefix_lookups,
            "kvBlocks": self.kv_blocks,
            "role": self.role,
            "shedTotal": self.shed,
            "degradedTotal": self.degraded,
        }

    @classmethod
    def parse(cls, data: dict) -> "ReplicaLoad":
        def _opt(key: str) -> Optional[float]:
            value = data.get(key)
            if value is None:
                return None
            try:
                return float(value)
            except (TypeError, ValueError):
                return None

        return cls(
            queue_depth=int(data.get("queueDepth") or 0),
            inflight=int(data.get("inflight") or 0),
            decode_token_s=float(data.get("decodeTokenS") or 0.0),
            gave_up=bool(data.get("gaveUp")),
            decode_mfu=_opt("decodeMfu"),
            host_gap_frac=_opt("hostGapFrac"),
            occupancy=_opt("occupancy"),
            steps=int(data.get("steps") or 0),
            slo_attainment=_opt("sloAttainment"),
            goodput_tokens_s=_opt("goodput"),
            slo_completed=int(data.get("sloCompleted") or 0),
            slo_classes=(
                data.get("sloClasses")
                if isinstance(data.get("sloClasses"), dict) else None
            ),
            kv_pages_free=int(data.get("kvPagesFree") or 0),
            kv_pages_total=int(data.get("kvPagesTotal") or 0),
            prefix_hit_rate=_opt("prefixHitRate"),
            prefix_lookups=int(data.get("kvLookups") or 0),
            kv_blocks=(
                [str(h) for h in data["kvBlocks"]]
                if isinstance(data.get("kvBlocks"), list) else None
            ),
            role=str(data.get("role") or "mixed"),
            shed=int(data.get("shedTotal") or 0),
            degraded=int(data.get("degradedTotal") or 0),
        )


class ReplicaHealth:
    """Passive health of one replica: EWMA latency, consecutive errors,
    last probe verdict, last load report."""

    #: EWMA smoothing for observed latency (~last 10 calls dominate)
    ALPHA = 0.2

    def __init__(self, clock: Optional[Callable[[], float]] = None) -> None:
        self._clock = clock or time.monotonic
        self.latency_ms: float = 0.0
        self.consecutive_errors: int = 0
        self.total_errors: int = 0
        self.total_calls: int = 0
        #: active-probe verdict; None = never probed (treated as ready —
        #: passive scoring and the breaker carry the gate until the first
        #: probe lands)
        self.probe_ready: Optional[bool] = None
        self.probed_at: float = 0.0
        self.load: ReplicaLoad = ReplicaLoad()
        self.load_at: float = 0.0

    def observe(self, *, ok: bool, latency_s: float = 0.0) -> None:
        self.total_calls += 1
        if ok:
            self.consecutive_errors = 0
            sample = latency_s * 1e3
            self.latency_ms = (
                sample if self.latency_ms == 0.0
                else (1 - self.ALPHA) * self.latency_ms + self.ALPHA * sample
            )
        else:
            self.consecutive_errors += 1
            self.total_errors += 1

    def report_load(self, load: ReplicaLoad) -> None:
        self.load = load
        self.load_at = self._clock()

    def mark_probe(self, ready: bool) -> None:
        self.probe_ready = ready
        self.probed_at = self._clock()

    @property
    def ready(self) -> bool:
        """Probe-level readiness: an explicit failing probe or a gave-up
        load report excludes the replica from routing until it recovers."""
        if self.load.gave_up:
            return False
        return self.probe_ready is not False

    def to_dict(self) -> dict:
        return {
            "latencyMs": round(self.latency_ms, 3),
            "consecutiveErrors": self.consecutive_errors,
            "totalErrors": self.total_errors,
            "totalCalls": self.total_calls,
            "probeReady": self.probe_ready,
            "load": self.load.to_dict(),
        }


class HealthBoard:
    """Per-replica health + breaker state behind one gate.

    Two admission questions, deliberately split: ``can_route`` is the
    PURE filter (no breaker transition, no probe consumption) the router
    asks about every replica while ranking candidates; ``admit`` is the
    consuming form the dispatcher calls for the ONE replica it is about
    to send to — in half-open, that dispatch IS the probe.  Passive
    observations feed the breaker, so a replica that dies without ever
    failing a probe still drains within ``failure_threshold`` calls."""

    def __init__(
        self,
        *,
        failure_threshold: int = 3,
        reset_s: float = 10.0,
        clock: Optional[Callable[[], float]] = None,
    ) -> None:
        self._clock = clock or time.monotonic
        self.breakers = BreakerBoard(failure_threshold, reset_s, clock=clock)
        self._health: dict[str, ReplicaHealth] = {}
        # fabric block index (operator_tpu/fabric/index.py): the active
        # form of the kvBlocks inventory — replace-on-report staleness
        # tombstones, fed by report_load() below, aged by remove() and
        # breaker opens, and evicted entry-by-entry on fetch 404s
        from ..fabric.index import FabricIndex

        self.kv_index = FabricIndex()

    def for_replica(self, replica_id: str) -> ReplicaHealth:
        health = self._health.get(replica_id)
        if health is None:
            health = ReplicaHealth(clock=self._clock)
            self._health[replica_id] = health
        return health

    def can_route(self, replica_id: str) -> bool:
        """Pure filter: would an attempt be admitted now?  Never mutates
        breaker state (see class doc)."""
        return (
            self.for_replica(replica_id).ready
            and self.breakers.for_key(replica_id).can_attempt()
        )

    def admit(self, replica_id: str) -> bool:
        """CONSUME admission for a call about to dispatch: transitions
        open→half-open when the reset window elapsed (this caller is the
        probe) and claims the probe token."""
        return (
            self.for_replica(replica_id).ready
            and self.breakers.for_key(replica_id).allow()
        )

    def observe_success(self, replica_id: str, latency_s: float) -> None:
        self.for_replica(replica_id).observe(ok=True, latency_s=latency_s)
        self.breakers.for_key(replica_id).record_success()

    def observe_failure(self, replica_id: str) -> bool:
        """Returns True when this failure OPENED the replica's breaker
        (the caller's cue to count the exclusion once)."""
        health = self.for_replica(replica_id)
        health.observe(ok=False)
        opened = self.breakers.for_key(replica_id).record_failure()
        if opened:
            # age the KV inventory with the breaker: an unreachable
            # replica's blocks must stop matching immediately, not
            # linger until its (never-arriving) next load report
            health.load.kv_blocks = None
            self.kv_index.remove(replica_id)
        return opened

    def report_load(
        self, replica_id: str, load: ReplicaLoad, *, url: str = ""
    ) -> None:
        """Land a load report AND refresh the fabric index in one step —
        the replace semantics ARE the staleness tombstone (anything the
        replica stopped advertising is unmatchable as of this report)."""
        self.for_replica(replica_id).report_load(load)
        self.kv_index.update(replica_id, load.kv_blocks, url=url)

    def remove(self, replica_id: str) -> None:
        """Forget a replica that left the ring (discovery leave, scale
        down): health entry, breaker, and its whole fabric inventory —
        a removed replica's blocks must never match again."""
        self._health.pop(replica_id, None)
        self.breakers.remove(replica_id)
        self.kv_index.remove(replica_id)

    def states(self) -> dict[str, dict]:
        return {
            replica_id: {
                "breaker": self.breakers.for_key(replica_id).state,
                **health.to_dict(),
            }
            for replica_id, health in sorted(self._health.items())
        }

    def fleet_view(self) -> dict:
        """Fleet perf roll-up for the operator's ``GET /fleet``: every
        replica's step-clock summary (as last reported on ``/healthz``)
        plus fleet aggregates (see :func:`fleet_rollup`)."""
        replicas = {}
        for replica_id, health in sorted(self._health.items()):
            load = health.load
            replicas[replica_id] = {
                "ready": health.ready,
                "breaker": self.breakers.for_key(replica_id).state,
                "latencyMs": round(health.latency_ms, 3),
                "queueDepth": load.queue_depth,
                "inflight": load.inflight,
                "decodeMfu": load.decode_mfu,
                "hostGapFrac": load.host_gap_frac,
                "occupancy": load.occupancy,
                "steps": load.steps,
                "sloAttainment": load.slo_attainment,
                "goodput": load.goodput_tokens_s,
                "sloCompleted": load.slo_completed,
                "sloClasses": load.slo_classes,
                "kvPagesFree": load.kv_pages_free,
                "kvPagesTotal": load.kv_pages_total,
                "prefixHitRate": load.prefix_hit_rate,
                "kvLookups": load.prefix_lookups,
                "role": load.role,
                "shedTotal": load.shed,
                "degradedTotal": load.degraded,
            }
        return {"replicas": replicas, "fleet": fleet_rollup(replicas)}

    def holders(self, block_hash: str) -> list[str]:
        """Replica ids whose last load report advertised ``block_hash``
        (hex) in their KV inventory — the peer index a failover consults
        to resume onto a survivor that can re-prefill from cache instead
        of recomputing.  Reports are advisory (bounded MRU snapshot, may
        be stale): an empty answer means "no known holder", never "no
        holder".  The union of the fabric index (fed via
        :meth:`report_load`, aged by :meth:`remove`/breaker opens) and
        the legacy per-health scan, so direct ``ReplicaHealth``
        report_load callers stay visible."""
        found = set(self.kv_index.holders(block_hash))
        for replica_id, health in self._health.items():
            blocks = health.load.kv_blocks
            if blocks and block_hash in blocks:
                found.add(replica_id)
        return sorted(found)
