"""Consistent-hash ring — stable affinity placement across replica churn.

The router's placement goal (docs/ROBUSTNESS.md "Multi-replica data
plane") is cache locality: requests sharing a prompt prefix or an
incident fingerprint should land on the SAME replica, so its prefix
cache, ``ResponseCache`` and incident-recall cache actually hit — and
that mapping must survive replica churn.  A modulo over the replica list
remaps nearly every key when one replica joins or dies; a consistent
ring remaps only the keys the changed replica owned (~1/N of the space),
which is exactly the AIBrix-style property the scale-out item asks for
(PAPERS.md: arxiv 2504.03648).

Implementation: each replica contributes ``vnodes`` points on a 2^64
ring (sha256 over ``"<id>#<i>"``), a key hashes to a point, and
ownership walks clockwise.  :meth:`preference` returns the full distinct
walk order — the failover/shed candidates in affinity order — so callers
apply health gating and load feedback WITHOUT consulting the ring twice.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Iterable, Optional

__all__ = ["HashRing"]


def _point(basis: str) -> int:
    return int.from_bytes(hashlib.sha256(basis.encode()).digest()[:8], "big")


class HashRing:
    """Consistent hashing with virtual nodes; not thread-safe on its own
    (the owning router serializes mutation under its lock)."""

    def __init__(self, replica_ids: Optional[Iterable[str]] = None, *,
                 vnodes: int = 64) -> None:
        self.vnodes = max(1, vnodes)
        self._points: list[int] = []       # sorted ring positions
        self._owner: dict[int, str] = {}   # position -> replica id
        self._ids: set[str] = set()
        for replica_id in replica_ids or ():
            self.add(replica_id)

    def add(self, replica_id: str) -> None:
        if replica_id in self._ids:
            return
        self._ids.add(replica_id)
        for i in range(self.vnodes):
            point = _point(f"{replica_id}#{i}")
            # sha collisions across 8-byte points are ~impossible at fleet
            # scale; first owner keeps a contested point (deterministic)
            if point in self._owner:
                continue
            self._owner[point] = replica_id
            bisect.insort(self._points, point)

    def remove(self, replica_id: str) -> None:
        if replica_id not in self._ids:
            return
        self._ids.discard(replica_id)
        dead = [p for p, owner in self._owner.items() if owner == replica_id]
        for point in dead:
            del self._owner[point]
        dead_set = set(dead)
        self._points = [p for p in self._points if p not in dead_set]

    def replicas(self) -> list[str]:
        return sorted(self._ids)

    def __len__(self) -> int:
        return len(self._ids)

    def owner(self, key: str) -> Optional[str]:
        """The replica owning ``key`` (None on an empty ring)."""
        order = self.preference(key, limit=1)
        return order[0] if order else None

    def preference(self, key: str, *, limit: Optional[int] = None) -> list[str]:
        """Distinct replica ids in clockwise walk order from ``key``'s
        ring position — element 0 is the affinity owner, the rest are the
        failover order.  ``limit`` stops the walk early."""
        if not self._points:
            return []
        want = limit if limit is not None else len(self._ids)
        start = bisect.bisect(self._points, _point(key))
        seen: list[str] = []
        for i in range(len(self._points)):
            owner = self._owner[self._points[(start + i) % len(self._points)]]
            if owner not in seen:
                seen.append(owner)
                if len(seen) >= want:
                    break
        return seen
