"""Resilient multi-engine data plane (docs/ROBUSTNESS.md).

A health-gated, affinity-aware failover router in front of N
``ServingEngine`` replicas: consistent-hash placement on prompt prefix /
incident fingerprint (``ring.py``), per-replica breakers + passive
scoring + load reports (``health.py``), and requeue-once failover with
residual deadlines (``core.py``).
"""

from .core import (
    EngineRouter,
    Replica,
    RouteDecision,
    RouteOutcome,
    RouterError,
    request_key,
)
from .health import (
    BreakerBoard,
    CircuitBreaker,
    HealthBoard,
    ReplicaHealth,
    ReplicaLoad,
)
from .ring import HashRing

__all__ = [
    "BreakerBoard",
    "CircuitBreaker",
    "EngineRouter",
    "HashRing",
    "HealthBoard",
    "Replica",
    "ReplicaHealth",
    "ReplicaLoad",
    "RouteDecision",
    "RouteOutcome",
    "RouterError",
    "request_key",
]
