"""Resilient multi-engine data plane (docs/ROBUSTNESS.md).

A health-gated, affinity-aware failover router in front of N
``ServingEngine`` replicas: consistent-hash placement on prompt prefix /
incident fingerprint (``ring.py``), per-replica breakers + passive
scoring + load reports (``health.py``), and requeue-once failover with
residual deadlines (``core.py``).  ``resume.py`` adds token-level
streaming resume: journaled generated-so-far checkpoints that turn a
mid-stream replica death into one (mostly cached) re-prefill on a
survivor instead of a full re-decode.
"""

from .core import (
    EngineRouter,
    Replica,
    RouteDecision,
    RouteOutcome,
    RouterError,
    request_key,
)
from .discovery import EndpointDiscovery, endpoint_urls
from .health import (
    BreakerBoard,
    CircuitBreaker,
    HealthBoard,
    ReplicaHealth,
    ReplicaLoad,
)
from .resume import ResumeLog
from .ring import HashRing
from .value import (
    OverloadPolicy,
    OverloadVerdict,
    RequestValue,
    ShedDecisionLog,
    ValueModel,
)

__all__ = [
    "BreakerBoard",
    "CircuitBreaker",
    "EndpointDiscovery",
    "EngineRouter",
    "HashRing",
    "HealthBoard",
    "OverloadPolicy",
    "OverloadVerdict",
    "Replica",
    "ReplicaHealth",
    "ReplicaLoad",
    "RequestValue",
    "ResumeLog",
    "RouteDecision",
    "RouteOutcome",
    "RouterError",
    "ShedDecisionLog",
    "ValueModel",
    "endpoint_urls",
    "request_key",
]
