"""Device<->host KV page transfer for the prefix-cache offload tier.

Evicted prefix blocks (serving/kvstore.py) spill device→host into a
pinned numpy pool sized by ``KV_HOST_POOL_MB`` and are restored on the
next hit — restore costs one page DMA + a table write instead of
re-prefilling the block.  The transfer discipline keeps the decode hot
path clean:

- ``gather_page`` is an EAGER device-side slice: it enqueues a copy of
  the page into a fresh buffer without any host sync, so eviction can
  re-grant the page immediately (device-order serialisation guarantees
  the gather reads the page before the new owner's writes land, and the
  gathered buffer is independent of later donation of the main cache).
- ``fetch_page`` is the ONE deliberate device→host sync, and the
  scheduler calls it only inside the commit step's existing host sync
  window (overlapped with the token fetch it already pays for).
- ``restore_page`` is a jitted donated in-place page write + one
  host→device transfer of the pooled numpy block.
"""

from __future__ import annotations

import functools
import threading
from collections import OrderedDict
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


class HostKVPool:
    """LRU host-RAM pool of offloaded KV blocks, keyed by block hash.

    Entries are (k, v) numpy arrays of one page each —
    ``[layers, page_size, kv_heads, head_dim]``.  ``capacity_mb`` bounds
    the pool; inserting past it drops least-recently-used blocks first.
    ``capacity_mb=0`` disables the pool (has() is always False), which
    turns eviction into plain forgetting.

    Thread-safe: with the KV fabric enabled the pool is read by the
    HTTP handler thread (``GET /kv/blocks/{hash}``) and the event loop's
    prefetch adoption while the decode worker drains offloads/mirrors
    into it, so every entry mutation happens under one lock.
    """

    def __init__(self, capacity_mb: int = 0) -> None:
        self.capacity_bytes = int(capacity_mb) * 1024 * 1024
        self._entries: "OrderedDict[bytes, tuple[np.ndarray, np.ndarray]]" = (
            OrderedDict()
        )
        self._lock = threading.Lock()
        self.bytes_used = 0
        self.dropped = 0  # blocks LRU-dropped to make room

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def has(self, h: bytes) -> bool:
        with self._lock:
            return h in self._entries

    def get(self, h: bytes) -> Optional[tuple[np.ndarray, np.ndarray]]:
        with self._lock:
            entry = self._entries.get(h)
            if entry is not None:
                self._entries.move_to_end(h)
            return entry

    def put(
        self, h: bytes, k: np.ndarray, v: np.ndarray
    ) -> Optional[list[bytes]]:
        """Insert a block.  Returns None when the pool is disabled or the
        single block exceeds capacity (caller should forget the hash);
        otherwise the list of LRU-dropped hashes (possibly empty) — the
        caller forgets those in its index so matches cannot go stale."""
        size = k.nbytes + v.nbytes
        if self.capacity_bytes <= 0 or size > self.capacity_bytes:
            return None
        with self._lock:
            if h in self._entries:
                self._entries.move_to_end(h)
                return []
            evicted: list[bytes] = []
            while (
                self.bytes_used + size > self.capacity_bytes and self._entries
            ):
                old, (ok, ov) = self._entries.popitem(last=False)
                self.bytes_used -= ok.nbytes + ov.nbytes
                self.dropped += 1
                evicted.append(old)
            self._entries[h] = (k, v)
            self.bytes_used += size
            return evicted

    def drop(self, h: bytes) -> None:
        with self._lock:
            entry = self._entries.pop(h, None)
            if entry is not None:
                self.bytes_used -= entry[0].nbytes + entry[1].nbytes


def gather_page(paged, page: int) -> tuple[jax.Array, jax.Array]:
    """Eagerly slice one page out of the cache into fresh device buffers.

    No host sync: the copy is enqueued on the device stream, so it is
    ordered before any later rewrite of the page, and the result buffer
    is safe from subsequent donation of the main cache arrays.
    Shapes: ``[layers, page_size, kv_heads, head_dim]`` each.
    """
    return paged.k_pages[:, page], paged.v_pages[:, page]


def fetch_page(k_dev: jax.Array, v_dev: jax.Array) -> tuple[np.ndarray, np.ndarray]:
    """Materialise a gathered page on the host — the ONE deliberate sync
    of the offload path; the scheduler calls it only inside the commit
    step's existing host-sync window."""
    # graftlint: disable=GL001 reason=deliberate device->host readback: offload fetch runs inside the commit step's existing host sync window, never in the dispatch hot path
    k = jax.device_get(k_dev)
    # graftlint: disable=GL001 reason=same deliberate offload readback as the k fetch above
    v = jax.device_get(v_dev)
    return np.asarray(k), np.asarray(v)


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _write_page(k_pages, v_pages, page, k, v):
    return (
        k_pages.at[:, page].set(k.astype(k_pages.dtype)),
        v_pages.at[:, page].set(v.astype(v_pages.dtype)),
    )


def restore_page(paged, page: int, k: np.ndarray, v: np.ndarray):
    """Write a pooled host block back into device page ``page``.

    One host→device transfer per array + a donated in-place page write;
    returns a new PagedKVCache sharing table/lengths with the input
    (whose k/v buffers are consumed by donation)."""
    k_pages, v_pages = _write_page(
        paged.k_pages, paged.v_pages, jnp.int32(page), k, v
    )
    return type(paged)(
        k_pages=k_pages,
        v_pages=v_pages,
        page_table=paged.page_table,
        lengths=paged.lengths,
    )
