"""Flash prefill attention: Pallas kernel for the batched-prefill forward.

The prefill bucket self-attends over its own right-padded tokens (the
serving engine's mini-cache, serving/engine.py): q = kv, positions
``0..T``, per-row validity ``pos < lengths[b]``.  The chunked-XLA path
(models/llama.py ``_attention_chunked``) already bounds score memory; this
kernel additionally:

- never materialises scores in HBM at all (VMEM running max/sum/acc);
- skips kv blocks the causal mask zeroes (the j > q-block blocks) AND
  blocks past the row's valid length — the BlockSpec-free in-kernel walk
  DMAs only what contributes (same design as ops/paged_attention.py v2);
- with a sliding window, starts each q block's walk at the first
  in-window kv block.

Gated off by default (OPERATOR_TPU_FLASH_PREFILL=1 enables) until
validated on hardware; the dense/chunked XLA paths remain the oracle.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp

from ._flash_common import finalize, init_state, update_state

_LANE = 128
_NEG_INF = -1e30


def flash_prefill_enabled() -> bool:
    return os.environ.get("OPERATOR_TPU_FLASH_PREFILL", "0").strip() == "1"


def flash_prefill_supported(t: int, s: int, cache_offset) -> bool:
    """Trace-time gate: self-attention prefill shapes only — kv range is
    exactly the q range (mini-cache, offset 0) and T divides into blocks."""
    if t != s or t < 2:
        return False
    # graftlint: disable=GL002 reason=the isinstance guard short-circuits before any tracer comparison; a traced cache_offset yields False without concretising
    if not isinstance(cache_offset, int) or cache_offset != 0:
        return False
    q_block = min(128, t)
    return t % q_block == 0


def flash_prefill_reference(
    q: jax.Array,  # [B, T, QH, D]
    k: jax.Array,  # [B, T, KH, D]
    v: jax.Array,
    lengths: jax.Array,  # [B]
    sliding_window: Optional[int] = None,
) -> jax.Array:
    """Dense oracle (same math as models/llama._attention + its mask)."""
    b, t, qh, d = q.shape
    kh = k.shape[2]
    g = qh // kh
    positions = jnp.arange(t, dtype=jnp.int32)
    causal = positions[None, :] <= positions[:, None]  # [T, S]
    valid = positions[None, None, :] < lengths[:, None, None]  # [B, 1, S]
    mask = causal[None] & valid
    if sliding_window is not None:
        mask = mask & (positions[None, :] > positions[:, None] - sliding_window)[None]
    q_grouped = q.reshape(b, t, kh, g, d)
    scores = jnp.einsum(
        "btkgd,bskd->bkgts", q_grouped, k, preferred_element_type=jnp.float32
    ) * (d**-0.5)
    scores = jnp.where(mask[:, None, None, :, :], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jnp.einsum("bkgts,bskd->btkgd", probs, v)
    return out.reshape(b, t, qh * d).astype(q.dtype)


def _flash_prefill_kernel(
    # scalar prefetch
    len_ref,  # [B] int32 (SMEM)
    # blocks
    q_ref,  # [1, q_block, 1, G, D] (VMEM)
    k_hbm,  # [B, KH, S, D] (HBM) — head-major: the per-head DMA below
    # slices a FULL head plane, so the tiled trailing dims (S, D) keep
    # their extents and bf16's (8,128)x2 tiling stays aligned (a [B, S,
    # KH, D] layout put KH in the tiled pair and its size-1 slice failed
    # Mosaic lowering for bf16 — caught by scripts/aot_tpu_check.py)
    v_hbm,
    out_ref,  # [1, q_block, 1, G, D] f32
    # scratch
    k_buf,  # [2, kv_block, D] VMEM double buffer
    v_buf,
    sem,  # DMA semaphores [2, 2]
    m_scratch,  # [rows, LANE] f32
    l_scratch,
    acc_scratch,  # [rows, D] f32
    *,
    q_block: int,
    kv_block: int,
    g: int,
    scale: float,
    window: Optional[int] = None,
):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b = pl.program_id(0)
    h = pl.program_id(1)
    i = pl.program_id(2)
    length = len_ref[b]
    rows = q_block * g

    # kv range this q block can touch: causal upper bound AND validity
    high = jnp.minimum(length, (i + 1) * q_block)
    nblocks = pl.cdiv(high, kv_block)  # 0 when the whole block is padding
    if window is not None:
        # earliest kv any row here can see: q_lo - window + 1
        first = jnp.maximum(i * q_block - window + 1, 0) // kv_block
    else:
        first = 0

    init_state(m_scratch, l_scratch, acc_scratch)

    def dma(slot, j):
        return (
            pltpu.make_async_copy(
                k_hbm.at[b, h, pl.ds(j * kv_block, kv_block)],
                k_buf.at[slot], sem.at[slot, 0],
            ),
            pltpu.make_async_copy(
                v_hbm.at[b, h, pl.ds(j * kv_block, kv_block)],
                v_buf.at[slot], sem.at[slot, 1],
            ),
        )

    @pl.when(nblocks > first)
    def _prologue():
        for copy in dma(first % 2, first):
            copy.start()

    q = q_ref[0, :, 0].astype(jnp.float32).reshape(rows, -1)  # [rows, D]
    # row r serves q position i*q_block + r // g
    q_pos = i * q_block + jax.lax.broadcasted_iota(
        jnp.int32, (rows, kv_block), 0
    ) // g

    def body(j, _):
        slot = j % 2

        @pl.when(j + 1 < nblocks)
        def _prefetch_next():
            for copy in dma((j + 1) % 2, j + 1):
                copy.start()

        for copy in dma(slot, j):
            copy.wait()

        k = k_buf[slot].astype(jnp.float32)  # [kv_block, D]
        v = v_buf[slot].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [rows, kv_block]

        kv_pos = j * kv_block + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = (kv_pos <= q_pos) & (kv_pos < length)
        if window is not None:
            mask = mask & (kv_pos > q_pos - window)
        s = jnp.where(mask, s, _NEG_INF)

        update_state(
            m_scratch, l_scratch, acc_scratch, s,
            lambda p: jax.lax.dot_general(
                p, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ),
        )
        return 0

    jax.lax.fori_loop(first, nblocks, body, 0)
    out = finalize(l_scratch, acc_scratch)  # [rows, D]
    out_ref[0, :, 0] = out.reshape(q_block, g, -1).astype(out_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("sliding_window", "q_block", "kv_block", "interpret")
)
def _flash_prefill_pallas(
    q: jax.Array,  # [B, T, QH, D]
    k: jax.Array,  # [B, T, KH, D]
    v: jax.Array,
    lengths: jax.Array,  # [B]
    *,
    sliding_window: Optional[int] = None,
    q_block: int = 128,
    kv_block: int = 128,
    interpret: bool = False,
) -> jax.Array:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, t, qh, d = q.shape
    kh = k.shape[2]
    g = qh // kh
    q_block = min(q_block, t)
    kv_block = min(kv_block, t)
    assert t % q_block == 0 and t % kv_block == 0, (t, q_block, kv_block)
    rows = q_block * g
    scale = d**-0.5

    kernel = functools.partial(
        _flash_prefill_kernel,
        q_block=q_block, kv_block=kv_block, g=g, scale=scale,
        window=sliding_window,
    )
    from ._dispatch import any_memory_space

    any_space = any_memory_space()
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, kh, t // q_block),
        in_specs=[
            pl.BlockSpec(
                (1, q_block, 1, g, d), lambda b, h, i, ln: (b, i, h, 0, 0)
            ),
            any_space,
            any_space,
        ],
        out_specs=pl.BlockSpec(
            (1, q_block, 1, g, d), lambda b, h, i, ln: (b, i, h, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((2, kv_block, d), k.dtype),
            pltpu.VMEM((2, kv_block, d), v.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
            pltpu.VMEM((rows, _LANE), jnp.float32),
            pltpu.VMEM((rows, _LANE), jnp.float32),
            pltpu.VMEM((rows, d), jnp.float32),
        ],
    )
    q5 = q.reshape(b, t, kh, g, d)
    # head-major K/V: the kernel DMAs one head's [kv_block, D] plane per
    # grid step, and with [B, KH, S, D] that slice keeps the tiled (S, D)
    # pair at full alignment for bf16 (see _flash_prefill_kernel)
    k_hm = jnp.swapaxes(k, 1, 2)
    v_hm = jnp.swapaxes(v, 1, 2)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, t, kh, g, d), jnp.float32),
        interpret=interpret,
    )(lengths, q5, k_hm, v_hm)
    return out.reshape(b, t, qh * d).astype(q.dtype)


def flash_prefill_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    lengths: jax.Array,
    sliding_window: Optional[int] = None,
) -> jax.Array:
    """Dispatch: Pallas kernel on TPU, dense oracle elsewhere."""
    from ._dispatch import on_tpu

    if on_tpu(q, k):
        return _flash_prefill_pallas(
            q, k, v, lengths, sliding_window=sliding_window
        )
    return flash_prefill_reference(q, k, v, lengths, sliding_window=sliding_window)
