"""Ragged mixed-phase paged attention: one kernel for prefill AND decode.

The serving engine historically ran two program families — batched
prefill over a right-padded ``[B, T]`` bucket and a fixed
``decode_block`` program over ``[B, 1]`` tokens — as separate phases, so
a long prefill stalled every in-flight decode and short decodes padded
out the block while the MXU idled (BENCH_r02: decode MFU 0.0064).  This
module is the kernel half of the fix (PAPERS.md: *Ragged Paged
Attention*, arxiv 2604.15464): ONE program where every batch row sits at
an arbitrary position — a decode row contributes one query token, a
prefill row contributes its next chunk — against the shared paged KV
cache (``ops/paged_attention.py`` layout).

Contract (KV is written to the pages BEFORE attention runs, so the
kernel is a pure read over the page pool):

    q         [B, C, QH, D]  this step's query tokens, row-padded past
                             ``q_count[b]`` (padding rows are ignored)
    k_pages   [num_pages, page_size, KH, D]  (single layer)
    v_pages   likewise
    page_table [B, pages_per_seq] int32
    kv_len    [B] int32  valid tokens in the row's pages INCLUDING this
                         step's writes
    q_count   [B] int32  live query rows this step (0 = inactive row)

Query token ``i`` of row ``b`` sits at absolute position
``kv_len[b] - q_count[b] + i`` and attends causally over positions
``<= `` its own.  A decode row is the ``q_count == 1`` special case; a
whole-prompt prefill is ``q_count == kv_len``; a mid-prompt chunk is
anything in between — one program covers all three, which is what lets
the scheduler (serving/sched/) dispatch a mixed wave every step.  A
speculation VERIFY row (sched/draft.py prompt-lookup drafts) is the same
geometry again: ``q_count = 1 + k`` query tokens — the committed last
token plus ``k`` drafts — where draft ``j`` at position
``kv_len - q_count + 1 + j`` causally attends over the committed context
AND every earlier draft, which is exactly the attention pattern
speculative verification needs; no kernel change, the scheduler just
samples all ``k + 1`` positions and accepts the longest confirmed
prefix (sched/mixed.py).

The Pallas kernel walks each row's live pages with in-kernel
double-buffered DMAs steered by the scalar-prefetched page table (the
``_paged_attn_kernel_v2`` design: exactly ``ceil(kv_len/page)`` pages
move from HBM) and keeps a flash-attention running (max, sum, acc) per
(query row, head) in VMEM.  The dense reference is the oracle for parity
tests and the CPU path.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from ._flash_common import finalize, init_state, update_state

_LANE = 128
_NEG_INF = -1e30


# ---------------------------------------------------------------------------
# dense reference (oracle + CPU path)
# ---------------------------------------------------------------------------


def ragged_attention_reference(
    q: jax.Array,  # [B, C, QH, D]
    k_pages: jax.Array,  # [num_pages, page_size, KH, D]
    v_pages: jax.Array,
    page_table: jax.Array,  # [B, pages_per_seq]
    kv_len: jax.Array,  # [B]
    q_count: jax.Array,  # [B]
    sliding_window: Optional[int] = None,
) -> jax.Array:
    """Gather-then-attend oracle.  Returns [B, C, QH, D] in q.dtype.

    Rows past ``q_count`` (and rows of inactive slots) produce garbage —
    callers gather only the valid rows, exactly as the kernel's
    flash-state finalize leaves NaN in fully-masked rows."""
    b, c, qh, d = q.shape
    kh = k_pages.shape[2]
    g = qh // kh
    page_size = k_pages.shape[1]
    max_seq = page_table.shape[1] * page_size

    k = k_pages[page_table].reshape(b, max_seq, kh, d)
    v = v_pages[page_table].reshape(b, max_seq, kh, d)

    q_grouped = q.reshape(b, c, kh, g, d)
    scores = jnp.einsum(
        "bckgd,bskd->bkgcs", q_grouped, k, preferred_element_type=jnp.float32
    ) * (d**-0.5)
    kv_pos = jnp.arange(max_seq, dtype=jnp.int32)[None, None, :]  # [1, 1, S]
    q_pos = (
        (kv_len - q_count)[:, None]
        + jnp.arange(c, dtype=jnp.int32)[None, :]
    )[:, :, None]  # [B, C, 1]
    mask = (kv_pos <= q_pos) & (kv_pos < kv_len[:, None, None])
    if sliding_window is not None:
        mask = mask & (kv_pos > q_pos - sliding_window)
    scores = jnp.where(mask[:, None, None, :, :], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jnp.einsum("bkgcs,bskd->bckgd", probs, v)
    return out.reshape(b, c, qh, d).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas kernel
# ---------------------------------------------------------------------------


def _ragged_attn_kernel(
    # scalar prefetch
    pt_ref,  # [B, pages_per_seq] int32 (SMEM)
    len_ref,  # [B] int32 kv_len (SMEM)
    cnt_ref,  # [B] int32 q_count (SMEM)
    # blocks
    q_ref,  # [1, C, QH, D] (VMEM)
    k_hbm,  # [num_pages, page_size, KH, D] (stays in HBM)
    v_hbm,
    out_ref,  # [1, C, QH, D] f32
    # scratch
    k_buf,  # [2, page_size, KH, D] VMEM double buffer
    v_buf,
    sem,  # DMA semaphores [2, 2]
    m_scratch,  # [C*QH, LANE] f32 running max
    l_scratch,  # [C*QH, LANE] f32 running denominator
    acc_scratch,  # [C*QH, D] f32
    *,
    c: int,
    kv_heads: int,
    q_per_kv: int,
    page_size: int,
    scale: float,
    window: Optional[int] = None,
):
    """One grid step per batch row; the row's q chunk rides a BlockSpec
    while its live KV pages stream through a manual double-buffered DMA
    walk (the ``ops/paged_attention.py`` v2 design).  Flash-state rows
    are laid out head-major — row ``h*C*G + i*G + j`` is query token
    ``i`` of q head ``h*G + j`` — so the per-kv-head GQA dots write
    contiguous slabs; the finalize transposes back to [C, QH, D]."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b = pl.program_id(0)
    seq_len = len_ref[b]
    count = cnt_ref[b]
    q_base = seq_len - count  # absolute position of q row 0
    # rows with no work this step (count == 0: inactive, or live but
    # unscheduled under a saturated token budget) walk ZERO pages — their
    # output is garbage by contract, so the DMAs and matmuls would be
    # pure waste exactly when the step is already compute-bound
    num_live = jnp.where(count > 0, pl.cdiv(seq_len, page_size), 0)
    first = 0
    if window is not None:
        # earliest kv ANY live q row can see: q_base - window + 1
        first = jnp.maximum(q_base - window + 1, 0) // page_size

    slab = c * q_per_kv  # flash rows per kv head (token-major within)
    total = kv_heads * slab

    init_state(m_scratch, l_scratch, acc_scratch)

    def dma(slot, j):
        return (
            pltpu.make_async_copy(
                k_hbm.at[pt_ref[b, j]], k_buf.at[slot], sem.at[slot, 0]
            ),
            pltpu.make_async_copy(
                v_hbm.at[pt_ref[b, j]], v_buf.at[slot], sem.at[slot, 1]
            ),
        )

    @pl.when(num_live > first)
    def _prologue():
        for copy in dma(first % 2, first):
            copy.start()

    q = q_ref[0].astype(jnp.float32)  # [C, QH, D]
    # flash rows: kv-head slabs stacked, token-major inside each — row
    # h*slab + i*G + j is query token i of q head h*G + j.  Its q
    # position depends only on the token index within the slab.
    row_iota = jax.lax.broadcasted_iota(jnp.int32, (total, page_size), 0)
    q_pos = q_base + (row_iota % slab) // q_per_kv

    def body(j, _):
        slot = j % 2

        @pl.when(j + 1 < num_live)
        def _prefetch_next():
            for copy in dma((j + 1) % 2, j + 1):
                copy.start()

        for copy in dma(slot, j):
            copy.wait()

        k = k_buf[slot]  # [page, KH, D]
        v = v_buf[slot]
        kv_pos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (total, page_size), 1
        )

        # scores for every slab against this page, stacked [total, page]
        parts = []
        for h in range(kv_heads):
            q_h = q[:, h * q_per_kv : (h + 1) * q_per_kv, :].reshape(slab, -1)
            k_h = k[:, h, :].astype(jnp.float32)  # [page, D]
            parts.append(
                jax.lax.dot_general(
                    q_h, k_h, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
            )
        s = jnp.concatenate(parts, axis=0) * scale
        mask = (kv_pos <= q_pos) & (kv_pos < seq_len)
        if window is not None:
            mask = mask & (kv_pos > q_pos - window)
        s = jnp.where(mask, s, _NEG_INF)

        def values(p):
            outs = []
            for h in range(kv_heads):
                p_h = p[h * slab : (h + 1) * slab]
                v_h = v[:, h, :].astype(jnp.float32)
                outs.append(
                    jax.lax.dot_general(
                        p_h, v_h, (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )
                )
            return jnp.concatenate(outs, axis=0)

        update_state(m_scratch, l_scratch, acc_scratch, s, values)
        return 0

    jax.lax.fori_loop(first, num_live, body, 0)
    out = finalize(l_scratch, acc_scratch)  # [KH*C*G, D]
    # slab h holds [C, G, D]; write it into the head band of [C, QH, D]
    for h in range(kv_heads):
        out_ref[0, :, h * q_per_kv : (h + 1) * q_per_kv, :] = (
            out[h * slab : (h + 1) * slab].reshape(c, q_per_kv, -1)
            .astype(out_ref.dtype)
        )


@functools.partial(jax.jit, static_argnames=("interpret", "sliding_window"))
def _ragged_attention_pallas(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    page_table: jax.Array,
    kv_len: jax.Array,
    q_count: jax.Array,
    *,
    interpret: bool = False,
    sliding_window: Optional[int] = None,
) -> jax.Array:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, c, qh, d = q.shape
    _, page_size, kh, _ = k_pages.shape
    scale = d**-0.5
    rows = c * qh  # total flash rows (all kv-head slabs stacked)

    kernel = functools.partial(
        _ragged_attn_kernel,
        c=c,
        kv_heads=kh,
        q_per_kv=qh // kh,
        page_size=page_size,
        scale=scale,
        window=sliding_window,
    )
    from ._dispatch import any_memory_space

    any_space = any_memory_space()
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, c, qh, d), lambda b, pt, ln, cn: (b, 0, 0, 0)),
            any_space,
            any_space,
        ],
        out_specs=pl.BlockSpec((1, c, qh, d), lambda b, pt, ln, cn: (b, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, page_size, kh, d), k_pages.dtype),
            pltpu.VMEM((2, page_size, kh, d), v_pages.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
            pltpu.VMEM((rows, _LANE), jnp.float32),
            pltpu.VMEM((rows, _LANE), jnp.float32),
            pltpu.VMEM((rows, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, c, qh, d), jnp.float32),
        interpret=interpret,
    )(page_table, kv_len, q_count, q, k_pages, v_pages)
    return out.astype(q.dtype)


def ragged_paged_attention(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    page_table: jax.Array,
    kv_len: jax.Array,
    q_count: jax.Array,
    sliding_window: Optional[int] = None,
) -> jax.Array:
    """Dispatch: Pallas kernel on TPU, dense reference elsewhere."""
    from ._dispatch import on_tpu

    if on_tpu(q, k_pages):
        return _ragged_attention_pallas(
            q, k_pages, v_pages, page_table, kv_len, q_count,
            sliding_window=sliding_window,
        )
    return ragged_attention_reference(
        q, k_pages, v_pages, page_table, kv_len, q_count,
        sliding_window=sliding_window,
    )
