"""Embedding-similarity scoring: log windows × pattern library.

The semantic pattern path (SURVEY.md §7 stage 3) embeds every log window
and every pattern description, then scores ``windows @ patterns.T``.  Both
sides are L2-normalised so the dot product *is* cosine similarity.

The fused Pallas kernel streams window blocks from HBM and keeps only the
per-pattern running max (score + argmax window) in VMEM — the full
``[num_windows, num_patterns]`` score matrix never touches HBM.  For a
10k-window log against a 1k-pattern library that skips a 40 MB round trip;
the op becomes pure compute on the MXU plus an O(P) output.

Shapes (D = embedding dim, a multiple of 128 by construction — MiniLM 384):

    windows  [W, D]  float32/bfloat16, L2-normalised rows
    patterns [P, D]  same dtype
    -> scores [P] float32, best_window [P] int32
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_LANE = 128
_BLOCK_W = 256  # window rows streamed per grid step


def _pad_to(x: jax.Array, size: int, axis: int) -> jax.Array:
    pad = size - x.shape[axis]
    if pad <= 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _round_up(n: int, m: int) -> int:
    return (n + m - 1) // m * m


# ---------------------------------------------------------------------------
# reference implementations (also the CPU execution path)
# ---------------------------------------------------------------------------


def similarity_matrix(windows: jax.Array, patterns: jax.Array) -> jax.Array:
    """Dense ``[W, P]`` cosine-score matrix (inputs assumed normalised)."""
    return jnp.einsum(
        "wd,pd->wp", windows, patterns, preferred_element_type=jnp.float32
    )


def best_window_scores_reference(
    windows: jax.Array, patterns: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """Per-pattern best window: (scores [P] f32, indices [P] i32)."""
    scores = similarity_matrix(windows, patterns)  # [W, P]
    return jnp.max(scores, axis=0), jnp.argmax(scores, axis=0).astype(jnp.int32)


def top_k_windows(
    windows: jax.Array, patterns: jax.Array, k: int
) -> tuple[jax.Array, jax.Array]:
    """Top-k windows by best-pattern score (for prompt context selection).

    Returns (scores [k] f32, window indices [k] i32), descending.  This is
    how long logs fit the LLM context budget: the serving prompt takes the
    k highest-evidence windows instead of the raw log (SURVEY.md §5
    long-context entry).
    """
    per_window = jnp.max(similarity_matrix(windows, patterns), axis=1)  # [W]
    k = min(k, per_window.shape[0])
    scores, idx = jax.lax.top_k(per_window, k)
    return scores, idx.astype(jnp.int32)


# ---------------------------------------------------------------------------
# fused Pallas kernel
# ---------------------------------------------------------------------------


def _best_window_kernel(
    w_ref,  # [BLOCK_W, D] window block (VMEM)
    p_ref,  # [P_pad, D] full pattern matrix (VMEM)
    scores_out,  # [P_pad] f32
    idx_out,  # [P_pad] i32
    max_scratch,  # [1, P_pad] f32 running max
    idx_scratch,  # [1, P_pad] i32 running argmax
    *,
    num_windows: int,
    block_w: int,
):
    from jax.experimental import pallas as pl

    step = pl.program_id(0)
    num_steps = pl.num_programs(0)

    @pl.when(step == 0)
    def _init():
        max_scratch[...] = jnp.full_like(max_scratch, -jnp.inf)
        idx_scratch[...] = jnp.zeros_like(idx_scratch)

    # [BLOCK_W, P_pad] on the MXU, f32 accumulation
    scores = jax.lax.dot_general(
        w_ref[...],
        p_ref[...],
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    # mask padded window rows (static shapes: W known at trace time)
    row = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 0) + step * block_w
    valid = row < num_windows
    scores = jnp.where(valid, scores, -jnp.inf)

    block_max = jnp.max(scores, axis=0, keepdims=True)  # [1, P_pad]
    # manual argmax: Mosaic lowers neither argmax nor integer reductions
    # (this jax version fails AOT on both) — take the SMALLEST row index
    # achieving the max (jnp.argmax's first-match tie-breaking), with the
    # min computed in f32.  Exact while window indices stay below 2^24
    # (~16.7M windows; a 1 GiB log at 256-byte stride is ~4M).
    is_max = scores == block_max  # [BLOCK_W, P_pad] vs broadcast [1, P_pad]
    block_arg = jnp.min(
        jnp.where(is_max, row.astype(jnp.float32), jnp.inf),
        axis=0,
        keepdims=True,
    ).astype(jnp.int32)

    better = block_max > max_scratch[...]
    idx_scratch[...] = jnp.where(better, block_arg, idx_scratch[...])
    max_scratch[...] = jnp.where(better, block_max, max_scratch[...])

    @pl.when(step == num_steps - 1)
    def _finish():
        scores_out[...] = max_scratch[0, :]
        idx_out[...] = idx_scratch[0, :]


@functools.partial(jax.jit, static_argnames=("block_w", "interpret"))
def _best_window_pallas(
    windows: jax.Array,
    patterns: jax.Array,
    *,
    block_w: int = _BLOCK_W,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    num_windows, dim = windows.shape
    num_patterns = patterns.shape[0]
    assert patterns.shape[1] == dim, "embedding dims must match"

    p_pad = _round_up(num_patterns, _LANE)
    w_pad = _round_up(num_windows, block_w)
    windows = _pad_to(windows, w_pad, 0)
    patterns = _pad_to(patterns, p_pad, 0)

    kernel = functools.partial(
        _best_window_kernel, num_windows=num_windows, block_w=block_w
    )
    scores, idx = pl.pallas_call(
        kernel,
        grid=(w_pad // block_w,),
        in_specs=[
            pl.BlockSpec((block_w, dim), lambda i: (i, 0)),
            pl.BlockSpec((p_pad, dim), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((p_pad,), lambda i: (0,)),
            pl.BlockSpec((p_pad,), lambda i: (0,)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((p_pad,), jnp.float32),
            jax.ShapeDtypeStruct((p_pad,), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, p_pad), jnp.float32),
            pltpu.VMEM((1, p_pad), jnp.int32),
        ],
        interpret=interpret,
    )(windows, patterns)
    return scores[:num_patterns], idx[:num_patterns]


def best_window_scores(
    windows: jax.Array, patterns: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """Dispatch: fused Pallas kernel on TPU, XLA reference elsewhere."""
    from ._dispatch import on_tpu

    if on_tpu(windows, patterns):
        return _best_window_pallas(windows, patterns)
    return best_window_scores_reference(windows, patterns)
