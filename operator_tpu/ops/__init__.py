"""Pallas TPU kernels with pure-XLA reference implementations.

Every kernel here has a ``*_reference`` twin built from plain ``jnp`` ops.
The references serve three roles: (1) parity oracles for the kernel tests,
(2) the actual execution path on CPU/interpret backends, and (3) readable
specifications of the math.  Callers go through the dispatching wrappers
(``best_window_scores``, ``paged_attention``) which pick the kernel on TPU
and the reference elsewhere.

Reference-system context (SURVEY.md §2.2): the external log-parser service
the reference called over REST is rebuilt as in-tree scoring; its hot op —
pattern-embedding × log-window-embedding similarity — lives here.  The
paged-attention kernel backs the serving engine's default paged-KV decode
(serving/engine.py BatchedGenerator(paged=True): page allocator, partial
admission backpressure) so batch-32 at 8B scale doesn't reserve worst-case
HBM per slot (BASELINE config 4, SURVEY.md §7 hard part c).
"""

from .similarity import (
    best_window_scores,
    best_window_scores_reference,
    similarity_matrix,
    top_k_windows,
)
from .paged_attention import (
    PagedKVCache,
    paged_attention,
    paged_attention_reference,
)
from .ragged_attention import (
    ragged_attention_reference,
    ragged_paged_attention,
)

__all__ = [
    "best_window_scores",
    "best_window_scores_reference",
    "similarity_matrix",
    "top_k_windows",
    "PagedKVCache",
    "paged_attention",
    "paged_attention_reference",
    "ragged_attention_reference",
    "ragged_paged_attention",
]
