"""Shared online-softmax state update for the flash-style Pallas kernels.

Both the paged decode kernel (paged_attention.py v2) and the prefill kernel
(flash_prefill.py) keep running (max, denominator, accumulator) state in
VMEM scratch and fold one masked score block in per step.  The update lives
here once so a numerics fix (rescaling, the lane-broadcast layout, the
denominator guard) cannot drift between them.

State layout: ``m``/``l`` are ``[rows, LANE]`` float32 with the scalar
duplicated across lanes (TPU vectors want a 128-wide last dim); ``acc`` is
``[rows, D]`` float32.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

LANE = 128
NEG_INF = -1e30


def init_state(m_scratch, l_scratch, acc_scratch) -> None:
    m_scratch[...] = jnp.full_like(m_scratch, NEG_INF)
    l_scratch[...] = jnp.zeros_like(l_scratch)
    acc_scratch[...] = jnp.zeros_like(acc_scratch)


def update_state(m_scratch, l_scratch, acc_scratch, s, o_block) -> jax.Array:
    """Fold one masked score block ``s`` [rows, block] into the running
    state.  ``o_block(p)`` maps the [rows, block] probabilities to the
    block's [rows, D] value contribution (the p @ V dot, shaped by the
    caller).  Returns nothing useful; mutates the scratch refs."""
    m_prev = m_scratch[...]
    l_prev = l_scratch[...]
    block_max = jnp.max(s, axis=1, keepdims=True)  # [rows, 1]
    m_new = jnp.maximum(
        m_prev, jax.lax.broadcast_in_dim(block_max, m_prev.shape, (0, 1))
    )
    alpha = jnp.exp(m_prev[:, :1] - m_new[:, :1])  # [rows, 1]
    p = jnp.exp(s - m_new[:, :1])  # [rows, block]
    l_scratch[...] = jax.lax.broadcast_in_dim(
        alpha * l_prev[:, :1] + jnp.sum(p, axis=1, keepdims=True),
        l_prev.shape, (0, 1),
    )
    m_scratch[...] = m_new
    acc_scratch[...] = acc_scratch[...] * alpha + o_block(p)
    return p


def finalize(l_scratch, acc_scratch) -> jax.Array:
    """acc / max(l, eps): zero rows (nothing attended) come out as zeros."""
    return acc_scratch[...] / jnp.maximum(l_scratch[:, :1], 1e-30)
