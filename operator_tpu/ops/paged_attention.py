"""Paged KV-cache attention for batched variable-length decode.

The serving engine batches up to 32 concurrent failure-event explanations
(BASELINE config 4).  Their sequence lengths are ragged — a contiguous
``[B, max_seq]`` cache would reserve worst-case HBM for every slot, which
is exactly what kills batch size at 8B scale on v5e (SURVEY.md §7 hard
part c).  Instead KV lives in fixed-size pages:

    k_pages, v_pages  [num_pages, page_size, kv_heads, head_dim]
    page_table        [batch, pages_per_seq] int32  (page ids per sequence)
    lengths           [batch] int32                 (tokens currently held)

The Pallas kernel walks each sequence's page list with the page table as
*scalar prefetch* (the table is read on the scalar core before the grid
step, steering the DMA of exactly the pages the sequence owns — no gather
materialisation), keeping a flash-attention style running
(max, sum, acc) in VMEM.  Grouped-query heads are expanded in-kernel, so
repeated KV never hits HBM (same trick as models/llama.py's einsum).

The dense reference gathers pages into a contiguous cache and runs masked
softmax attention — the oracle for parity tests and the CPU path.
"""

from __future__ import annotations

import functools
import os
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

_LANE = 128
_NEG_INF = -1e30


# ---------------------------------------------------------------------------
# paged cache container + host-free update ops
# ---------------------------------------------------------------------------


@dataclass
class PagedKVCache:
    """Per-layer paged KV storage (layers stacked on axis 0 for lax.scan)."""

    k_pages: jax.Array  # [layers, num_pages, page_size, kv_heads, head_dim]
    v_pages: jax.Array
    page_table: jax.Array  # [batch, pages_per_seq] int32
    lengths: jax.Array  # [batch] int32

    @property
    def page_size(self) -> int:
        return self.k_pages.shape[2]

    @classmethod
    def create(
        cls,
        num_layers: int,
        num_pages: int,
        page_size: int,
        kv_heads: int,
        head_dim: int,
        batch_size: int,
        pages_per_seq: int,
        dtype: jnp.dtype = jnp.bfloat16,
    ) -> "PagedKVCache":
        shape = (num_layers, num_pages, page_size, kv_heads, head_dim)
        return cls(
            k_pages=jnp.zeros(shape, dtype),
            v_pages=jnp.zeros(shape, dtype),
            page_table=jnp.zeros((batch_size, pages_per_seq), jnp.int32),
            lengths=jnp.zeros((batch_size,), jnp.int32),
        )


jax.tree_util.register_pytree_node(
    PagedKVCache,
    lambda c: ((c.k_pages, c.v_pages, c.page_table, c.lengths), None),
    lambda _, ch: PagedKVCache(*ch),
)


def write_tokens(
    pages: jax.Array,  # [num_pages, page_size, KH, D] (single layer)
    page_table: jax.Array,  # [B, pages_per_seq]
    new: jax.Array,  # [B, T, KH, D] tokens to store
    start: jax.Array,  # [B] int32 position of new[:, 0]
    valid_len: Optional[jax.Array] = None,  # [B] tokens of new[] that are real
) -> jax.Array:
    """Scatter T new tokens per sequence into their pages (prefill or
    decode append — decode is T=1, start=lengths).

    Rows past ``valid_len`` (prefill padding) are redirected to page 0,
    which the allocator reserves as a trash page (serving/engine.py) — a
    padded row must never land in another sequence's pages.
    """
    b, t = new.shape[0], new.shape[1]
    page_size = pages.shape[1]
    positions = start[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]  # [B, T]
    page_ids = jnp.take_along_axis(
        page_table, positions // page_size, axis=1
    )  # [B, T]
    slots = positions % page_size
    if valid_len is not None:
        valid = jnp.arange(t, dtype=jnp.int32)[None, :] < valid_len[:, None]
        page_ids = jnp.where(valid, page_ids, 0)
        slots = jnp.where(valid, slots, 0)
    return pages.at[page_ids, slots].set(new.astype(pages.dtype))


# ---------------------------------------------------------------------------
# dense reference
# ---------------------------------------------------------------------------


def paged_attention_reference(
    q: jax.Array,  # [B, QH, D] current-token queries (RoPE applied)
    k_pages: jax.Array,  # [num_pages, page_size, KH, D] (single layer)
    v_pages: jax.Array,
    page_table: jax.Array,  # [B, pages_per_seq]
    lengths: jax.Array,  # [B] number of valid tokens (incl. current)
    sliding_window: Optional[int] = None,
) -> jax.Array:
    """Gather-then-attend oracle.  Returns [B, QH, D] in q.dtype."""
    b, qh, d = q.shape
    kh = k_pages.shape[2]
    g = qh // kh
    page_size = k_pages.shape[1]
    max_seq = page_table.shape[1] * page_size

    # [B, S, KH, D] contiguous gather of each sequence's pages
    k = k_pages[page_table].reshape(b, max_seq, kh, d)
    v = v_pages[page_table].reshape(b, max_seq, kh, d)

    q_grouped = q.reshape(b, kh, g, d)
    scores = jnp.einsum(
        "bkgd,bskd->bkgs", q_grouped, k, preferred_element_type=jnp.float32
    ) * (d**-0.5)
    positions = jnp.arange(max_seq, dtype=jnp.int32)[None, :]
    valid = positions < lengths[:, None]
    if sliding_window is not None:
        # the decoding token (position lengths-1) attends to the last
        # `window` tokens: positions >= lengths - window (same semantics
        # as make_causal_mask's `recent` term in models/llama.py)
        valid = valid & (positions >= lengths[:, None] - sliding_window)
    scores = jnp.where(valid[:, None, None, :], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jnp.einsum("bkgs,bskd->bkgd", probs, v)
    return out.reshape(b, qh, d).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas kernel
# ---------------------------------------------------------------------------

from ._flash_common import finalize, init_state, update_state  # noqa: E402


def _gqa_scores(q, k, kv_heads: int, q_per_kv: int) -> jax.Array:
    """[QH, D] q x [page, KH, D] k -> [QH, page] scores; GQA expanded via
    per-kv-head dots so repeated KV never materialises."""
    parts = []
    for h in range(kv_heads):
        q_h = q[h * q_per_kv : (h + 1) * q_per_kv]  # [G, D]
        k_h = k[:, h, :].astype(jnp.float32)  # [page, D]
        parts.append(
            jax.lax.dot_general(
                q_h, k_h, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        )
    return jnp.concatenate(parts, axis=0)


def _gqa_values(p, v, kv_heads: int, q_per_kv: int) -> jax.Array:
    """[QH, page] probabilities x [page, KH, D] v -> [QH, D]."""
    parts = []
    for h in range(kv_heads):
        p_h = p[h * q_per_kv : (h + 1) * q_per_kv]  # [G, page]
        v_h = v[:, h, :].astype(jnp.float32)  # [page, D]
        parts.append(
            jax.lax.dot_general(
                p_h, v_h, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        )
    return jnp.concatenate(parts, axis=0)


def _paged_attn_kernel(
    # scalar prefetch
    pt_ref,  # [B, pages_per_seq] int32 (SMEM)
    len_ref,  # [B] int32 (SMEM)
    # blocks
    q_ref,  # [1, QH, D]
    k_ref,  # [1, page_size, KH, D] — the page pt[b, j]
    v_ref,
    out_ref,  # [1, QH, D] f32
    # scratch
    m_scratch,  # [QH, LANE] f32 running max (lanes duplicated)
    l_scratch,  # [QH, LANE] f32 running denominator
    acc_scratch,  # [QH, D] f32
    *,
    kv_heads: int,
    q_per_kv: int,
    page_size: int,
    scale: float,
    window: Optional[int] = None,
):
    from jax.experimental import pallas as pl

    b = pl.program_id(0)
    j = pl.program_id(1)
    num_pages = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        init_state(m_scratch, l_scratch, acc_scratch)

    seq_len = len_ref[b]

    # only touch pages that hold live tokens — and, with a sliding window,
    # only pages overlapping [seq_len - window, seq_len)
    live = j * page_size < seq_len
    if window is not None:
        window_lo = jnp.maximum(seq_len - window, 0)
        live = jnp.logical_and(live, (j + 1) * page_size > window_lo)

    @pl.when(live)
    def _compute():
        q = q_ref[0].astype(jnp.float32)  # [QH, D]
        k = k_ref[0]  # [page, KH, D]
        v = v_ref[0]

        s = _gqa_scores(q, k, kv_heads, q_per_kv) * scale  # [QH, page]
        pos = j * page_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(pos < seq_len, s, _NEG_INF)
        if window is not None:
            s = jnp.where(pos >= window_lo, s, _NEG_INF)

        update_state(
            m_scratch, l_scratch, acc_scratch, s,
            lambda p: _gqa_values(p, v, kv_heads, q_per_kv),
        )

    @pl.when(j == num_pages - 1)
    def _finish():
        out_ref[0] = finalize(l_scratch, acc_scratch).astype(out_ref.dtype)


def _paged_attn_kernel_v2(
    # scalar prefetch
    pt_ref,  # [B, pages_per_seq] int32 (SMEM)
    len_ref,  # [B] int32 (SMEM)
    # blocks
    q_ref,  # [1, QH, D] (VMEM)
    k_hbm,  # [num_pages, page_size, KH, D] (stays in HBM)
    v_hbm,
    out_ref,  # [1, QH, D] f32
    # scratch
    k_buf,  # [2, page_size, KH, D] VMEM double buffer
    v_buf,
    sem,  # DMA semaphores [2, 2]
    m_scratch,  # [QH, LANE] f32
    l_scratch,
    acc_scratch,  # [QH, D] f32
    *,
    kv_heads: int,
    q_per_kv: int,
    page_size: int,
    scale: float,
    window: Optional[int] = None,
):
    """Decode paged attention, one grid step per sequence.

    The v1 kernel's grid was (B, pages_per_seq): every page slot cost a
    grid step and a BlockSpec DMA whether or not it held live tokens
    (the index map always fetches).  Here the page walk happens INSIDE the
    kernel with manual double-buffered DMAs steered by the scalar-prefetched
    page table, so exactly ceil(len/page) pages move from HBM — a sequence
    at length 100 with a 4096-token table reads 2 pages, not 64 — and page
    i+1's DMA overlaps page i's flash-attention update.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b = pl.program_id(0)
    seq_len = len_ref[b]
    num_live = pl.cdiv(seq_len, page_size)
    first = 0
    if window is not None:
        window_lo = jnp.maximum(seq_len - window, 0)
        first = window_lo // page_size

    init_state(m_scratch, l_scratch, acc_scratch)

    def dma(slot, j):
        return (
            pltpu.make_async_copy(k_hbm.at[pt_ref[b, j]], k_buf.at[slot], sem.at[slot, 0]),
            pltpu.make_async_copy(v_hbm.at[pt_ref[b, j]], v_buf.at[slot], sem.at[slot, 1]),
        )

    @pl.when(num_live > first)
    def _prologue():
        for copy in dma(first % 2, first):
            copy.start()

    def body(j, _):
        slot = j % 2

        @pl.when(j + 1 < num_live)
        def _prefetch_next():
            for copy in dma((j + 1) % 2, j + 1):
                copy.start()

        for copy in dma(slot, j):
            copy.wait()

        q = q_ref[0].astype(jnp.float32)  # [QH, D]
        k = k_buf[slot]  # [page, KH, D]
        v = v_buf[slot]

        s = _gqa_scores(q, k, kv_heads, q_per_kv) * scale  # [QH, page]
        pos = j * page_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(pos < seq_len, s, _NEG_INF)
        if window is not None:
            s = jnp.where(pos >= window_lo, s, _NEG_INF)

        update_state(
            m_scratch, l_scratch, acc_scratch, s,
            lambda p: _gqa_values(p, v, kv_heads, q_per_kv),
        )
        return 0

    jax.lax.fori_loop(first, num_live, body, 0)
    out_ref[0] = finalize(l_scratch, acc_scratch).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret", "sliding_window"))
def _paged_attention_pallas_v2(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    page_table: jax.Array,
    lengths: jax.Array,
    *,
    interpret: bool = False,
    sliding_window: Optional[int] = None,
) -> jax.Array:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, qh, d = q.shape
    _, page_size, kh, _ = k_pages.shape
    scale = d**-0.5

    kernel = functools.partial(
        _paged_attn_kernel_v2,
        kv_heads=kh,
        q_per_kv=qh // kh,
        page_size=page_size,
        scale=scale,
        window=sliding_window,
    )
    from ._dispatch import any_memory_space

    any_space = any_memory_space()
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, qh, d), lambda b, pt, ln: (b, 0, 0)),
            any_space,
            any_space,
        ],
        out_specs=pl.BlockSpec((1, qh, d), lambda b, pt, ln: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, page_size, kh, d), k_pages.dtype),
            pltpu.VMEM((2, page_size, kh, d), v_pages.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
            pltpu.VMEM((qh, _LANE), jnp.float32),
            pltpu.VMEM((qh, _LANE), jnp.float32),
            pltpu.VMEM((qh, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, qh, d), jnp.float32),
        interpret=interpret,
    )(page_table, lengths, q, k_pages, v_pages)
    return out.astype(q.dtype)


@functools.partial(jax.jit, static_argnames=("interpret", "sliding_window"))
def _paged_attention_pallas(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    page_table: jax.Array,
    lengths: jax.Array,
    *,
    interpret: bool = False,
    sliding_window: Optional[int] = None,
) -> jax.Array:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, qh, d = q.shape
    _, page_size, kh, _ = k_pages.shape
    pages_per_seq = page_table.shape[1]
    scale = d**-0.5

    kernel = functools.partial(
        _paged_attn_kernel,
        kv_heads=kh,
        q_per_kv=qh // kh,
        page_size=page_size,
        scale=scale,
        window=sliding_window,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, pages_per_seq),
        in_specs=[
            pl.BlockSpec((1, qh, d), lambda b, j, pt, ln: (b, 0, 0)),
            pl.BlockSpec(
                (1, page_size, kh, d), lambda b, j, pt, ln: (pt[b, j], 0, 0, 0)
            ),
            pl.BlockSpec(
                (1, page_size, kh, d), lambda b, j, pt, ln: (pt[b, j], 0, 0, 0)
            ),
        ],
        out_specs=pl.BlockSpec((1, qh, d), lambda b, j, pt, ln: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((qh, _LANE), jnp.float32),
            pltpu.VMEM((qh, _LANE), jnp.float32),
            pltpu.VMEM((qh, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, qh, d), jnp.float32),
        interpret=interpret,
    )(page_table, lengths, q, k_pages, v_pages)
    return out.astype(q.dtype)


def _kernel_version() -> str:
    """Which Pallas kernel serves decode on TPU: "v1" (BlockSpec page grid,
    every page slot DMA'd) or "v2" (in-kernel double-buffered DMA of live
    pages only).  v1 stays default until v2 is validated on hardware.  Read
    when a program is TRACED — already-compiled buckets keep whatever kernel
    they were built with, so set the env before the process starts rather
    than flipping it mid-flight.  Unknown values raise rather than silently
    benching the wrong kernel."""
    version = os.environ.get("OPERATOR_TPU_PAGED_KERNEL", "v1").strip().lower()
    if version not in ("v1", "v2"):
        raise ValueError(
            f"OPERATOR_TPU_PAGED_KERNEL={version!r}: expected 'v1' or 'v2'"
        )
    return version


def paged_attention(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    page_table: jax.Array,
    lengths: jax.Array,
    sliding_window: Optional[int] = None,
) -> jax.Array:
    """Dispatch: Pallas kernel on TPU, dense reference elsewhere."""
    from ._dispatch import on_tpu

    if on_tpu(q, k_pages):
        impl = (
            _paged_attention_pallas_v2
            if _kernel_version() == "v2"
            else _paged_attention_pallas
        )
        return impl(
            q, k_pages, v_pages, page_table, lengths, sliding_window=sliding_window
        )
    return paged_attention_reference(
        q, k_pages, v_pages, page_table, lengths, sliding_window=sliding_window
    )
