"""Kernel-vs-reference dispatch.

``jax.default_backend()`` alone is wrong here: environments with an
experimental TPU plugin keep reporting ``tpu`` even when tests pin the
default *device* to CPU (tests/conftest.py).  The committed device of the
input arrays is the truth; fall back to the configured default device,
then the backend.
"""

from __future__ import annotations

import jax


def on_tpu(*arrays: jax.Array) -> bool:
    for array in arrays:
        devices = getattr(array, "devices", None)
        if callable(devices):
            try:
                platforms = {d.platform for d in array.devices()}
            except Exception:  # pragma: no cover - uncommitted tracers
                continue
            if platforms:
                return platforms == {"tpu"}
    default = jax.config.jax_default_device
    if default is not None:
        return getattr(default, "platform", None) == "tpu"
    return jax.default_backend() == "tpu"


def any_memory_space():
    """``pl.BlockSpec(memory_space=ANY)`` across jax versions: the enum
    was renamed TPUMemorySpace -> MemorySpace around 0.4.38.  The ONE
    compat shim for every kernel that keeps an operand in HBM for manual
    DMA (paged_attention v2, flash_prefill, ragged_attention)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    memory_space = getattr(pltpu, "MemorySpace", None) or pltpu.TPUMemorySpace
    return pl.BlockSpec(memory_space=memory_space.ANY)
