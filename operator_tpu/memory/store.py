"""Bounded durable incident store.

One :class:`Incident` per distinct failure fingerprint, kept in an LRU
ordering with TTL expiry so the store tracks the fleet's CURRENT failure
population, not everything it ever saw.

Durability is an append-only JSONL journal (optional — ``path=None`` keeps
the store purely in-memory for tests and laptops):

- every mutation appends one line (``put`` = full incident, ``touch`` =
  recurrence bump), flushed immediately — crash-safe in the sense that a
  torn final line is detected and skipped at load, losing at most the one
  mutation that was mid-write;
- the journal compacts (rewrite to a temp file + ``os.replace``, the
  atomic-on-POSIX pattern) once it grows past ``compact_factor`` times the
  live entry count, so a 500x-recurring incident does not append 500
  copies of its analysis text.

An optional ConfigMap snapshot (``snapshot()``/``load_snapshot()``) gives
operators without a PVC a bounded recovery point: newest incidents first,
truncated to fit the apiserver's object-size comfort zone.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional

from ..schema.meta import now_iso
from ..schema.serde import from_dict, to_dict
from ..utils.journal import Journal

log = logging.getLogger(__name__)

#: ConfigMap payloads stay under this (the 256 KiB annotation guard's
#: big sibling: ConfigMaps cap at 1 MiB total; leave generous headroom)
MAX_SNAPSHOT_BYTES = 768 * 1024


@dataclass
class CachedAnalysis:
    """One provider's clean analysis of a failure class — the unit an
    exact hit reuses verbatim."""

    explanation: Optional[str] = None
    provider_id: Optional[str] = None
    model_id: Optional[str] = None


@dataclass
class Incident:
    """One remembered failure class: identity, recurrence accounting, and
    the cached analyses future exact hits reuse verbatim.

    Recurrence (``seen_count`` etc.) is per failure CLASS; the reusable
    analyses are per AIProvider ref (``analyses`` keyed by
    "namespace/name", "" for none) — two CRs watching one workload with
    different providers each reuse THEIR OWN text, never each other's."""

    fingerprint: Optional[str] = None
    pattern_ids: list[str] = field(default_factory=list)
    severity: Optional[str] = None
    template: str = ""
    exit_code: Optional[int] = None
    reason: Optional[str] = None
    #: the LATEST clean analysis text (display + near-hit prompt context;
    #: None while only pattern-only/degraded results exist for this class)
    explanation: Optional[str] = None
    provider_id: Optional[str] = None
    model_id: Optional[str] = None
    #: per-provider-ref reusable analyses (exact-hit reuse looks up the
    #: recalling CR's own ref here)
    analyses: dict[str, CachedAnalysis] = field(default_factory=dict)
    #: where this class was FIRST seen (display only — identity excludes it)
    pod_name: Optional[str] = None
    pod_namespace: Optional[str] = None
    first_seen: Optional[str] = None
    last_seen: Optional[str] = None
    #: wall-clock epoch of the last sighting (TTL arithmetic; the ISO
    #: strings above are for humans and the CR status)
    last_seen_ts: float = 0.0
    seen_count: int = 1
    #: how many of those sightings reused the cached analysis
    reused_count: int = 0
    #: fingerprints of near-miss incidents this analysis was linked to
    #: (retrieval-augmented context at generation time)
    related: list[str] = field(default_factory=list)
    #: flight-recorder trace id of the most recent sighting's analysis
    #: (operator_tpu/obs/) — a recurrence links straight to the prior
    #: timeline via GET /traces/{id}
    last_trace_id: Optional[str] = None

    def to_dict(self) -> dict:
        return to_dict(self)

    @classmethod
    def parse(cls, data: dict) -> "Incident":
        return from_dict(cls, data)


class IncidentStore:
    """Thread-safe bounded LRU of incidents keyed by fingerprint digest."""

    def __init__(
        self,
        path: Optional[str] = None,
        *,
        max_entries: int = 2048,
        ttl_s: float = 7 * 86400.0,
        compact_factor: int = 4,
        clock: Optional[Callable[[], float]] = None,
    ) -> None:
        self.path = path
        self.max_entries = max(1, max_entries)
        self.ttl_s = ttl_s
        self.compact_factor = max(2, compact_factor)
        self._clock = clock or time.time
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, Incident]" = OrderedDict()
        # shared crash-safe JSONL discipline (utils/journal.py): torn-line
        # tolerant load, append+flush, temp-file+os.replace compaction.
        # Direct (caller-thread) writes: store mutations already run off
        # the event loop via asyncio.to_thread.
        self._journal = Journal(path, label="incident journal")
        if path:
            with self._lock:
                self._journal.load(self._replay_locked)
                self._journal.open()
            log.info("incident store: %d incident(s) restored from %s",
                     len(self), path)

    def _replay_locked(self, record: dict) -> None:
        op = record.get("op")
        if op == "put":
            incident = Incident.parse(record["incident"])
            if incident.fingerprint:
                self._entries[incident.fingerprint] = incident
                self._entries.move_to_end(incident.fingerprint)
        elif op == "touch":
            incident = self._entries.get(record["fp"])
            if incident is not None:
                incident.seen_count = int(record.get("seen", incident.seen_count + 1))
                incident.reused_count = int(record.get("reused", incident.reused_count))
                incident.last_seen = record.get("last_seen", incident.last_seen)
                incident.last_seen_ts = float(record.get("ts", incident.last_seen_ts))
                # pre-obs journals have no trace field; keep what we had
                incident.last_trace_id = record.get("trace", incident.last_trace_id)
                self._entries.move_to_end(record["fp"])
        elif op == "evict":
            self._entries.pop(record.get("fp", ""), None)
        else:
            raise KeyError(f"unknown journal op {op!r}")

    def _append(self, record: dict) -> None:
        self._journal.append(record)
        if self._journal.lines > self.compact_factor * max(len(self._entries), 16):
            # one ``put`` per live incident — a 500x-recurring incident
            # must not keep 500 copies of its analysis text on disk
            self._journal.compact(
                [{"op": "put", "incident": incident.to_dict()}
                 for incident in self._entries.values()]
            )

    def close(self) -> None:
        with self._lock:
            self._journal.close()

    # -- mutation ------------------------------------------------------
    def upsert(self, incident: Incident, *, bump_if_existing: bool = False) -> list[str]:
        """Insert or update (same digest keeps first_seen and merges in
        the new analysis text).  ``bump_if_existing`` counts the sighting
        when the caller had NOT already recorded it via
        :meth:`record_recurrence` — the concurrent-first-sighting race
        (two recalls miss, two inserts land) must not undercount.
        Returns the digests EVICTED to make room — the caller's cue to
        drop index rows."""
        assert incident.fingerprint, "incident requires a fingerprint"
        now = self._clock()
        with self._lock:
            existing = self._entries.get(incident.fingerprint)
            if existing is not None:
                # recurrence accounting lives on the existing record; the
                # new record only contributes fresher analysis metadata
                if bump_if_existing:
                    existing.seen_count += 1
                existing.explanation = incident.explanation or existing.explanation
                existing.provider_id = incident.provider_id or existing.provider_id
                existing.model_id = incident.model_id or existing.model_id
                existing.analyses.update(incident.analyses)  # per-ref, new wins
                existing.severity = incident.severity or existing.severity
                for digest in incident.related:
                    if digest not in existing.related:
                        existing.related.append(digest)
                existing.last_seen = incident.last_seen or now_iso()
                existing.last_seen_ts = now
                existing.last_trace_id = incident.last_trace_id or existing.last_trace_id
                incident = existing
            else:
                incident.first_seen = incident.first_seen or now_iso()
                incident.last_seen = incident.last_seen or incident.first_seen
                incident.last_seen_ts = now
                self._entries[incident.fingerprint] = incident
            self._entries.move_to_end(incident.fingerprint)
            evicted = self._evict_locked(now)
            self._append({"op": "put", "incident": incident.to_dict()})
            for digest in evicted:
                self._append({"op": "evict", "fp": digest})
            return evicted

    def record_recurrence(
        self, digest: str, *, reused: bool = False, trace_id: Optional[str] = None
    ) -> Optional[Incident]:
        """Bump the sighting counters of an exact fingerprint hit; returns
        the updated incident (None when the digest is unknown).
        ``trace_id`` stamps this sighting's flight-recorder trace onto the
        incident so the NEXT recurrence can link back to it."""
        with self._lock:
            incident = self._entries.get(digest)
            if incident is None:
                return None
            incident.seen_count += 1
            if reused:
                incident.reused_count += 1
            incident.last_seen = now_iso()
            incident.last_seen_ts = self._clock()
            if trace_id:
                incident.last_trace_id = trace_id
            self._entries.move_to_end(digest)
            record = {
                "op": "touch", "fp": digest, "seen": incident.seen_count,
                "reused": incident.reused_count, "last_seen": incident.last_seen,
                "ts": incident.last_seen_ts,
            }
            if incident.last_trace_id:
                record["trace"] = incident.last_trace_id
            self._append(record)
            return incident

    def _evict_locked(self, now: float) -> list[str]:
        evicted: list[str] = []
        if self.ttl_s > 0:
            for digest in [
                d for d, inc in self._entries.items()
                if now - inc.last_seen_ts > self.ttl_s
            ]:
                self._entries.pop(digest)
                evicted.append(digest)
        while len(self._entries) > self.max_entries:
            digest, _ = self._entries.popitem(last=False)  # LRU tail
            evicted.append(digest)
        return evicted

    def expire(self) -> list[str]:
        """TTL sweep on demand (recall consults the store lazily; callers
        with no traffic can still age incidents out)."""
        with self._lock:
            evicted = self._evict_locked(self._clock())
            for digest in evicted:
                self._append({"op": "evict", "fp": digest})
            return evicted

    # -- queries -------------------------------------------------------
    def get(self, digest: str) -> Optional[Incident]:
        with self._lock:
            return self._entries.get(digest)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def all(self, newest_first: bool = True) -> list[Incident]:
        with self._lock:
            incidents = list(self._entries.values())
        return list(reversed(incidents)) if newest_first else incidents

    def to_dicts(
        self, newest_first: bool = True, limit: Optional[int] = None
    ) -> list[dict]:
        """Serialized snapshot taken UNDER the lock — Incident objects are
        live and mutated by worker threads (upsert merging a new analyses
        key), so serializing them lock-free can raise mid-iteration.
        ``limit`` bounds how many incidents are serialized (the lock is
        held for the duration; callers paging a 2048-entry store must not
        serialize all of it for a ?limit=5 request)."""
        with self._lock:
            incidents = list(self._entries.values())
            if newest_first:
                incidents.reverse()
            if limit is not None:
                incidents = incidents[: max(0, limit)]
            return [to_dict(i) for i in incidents]

    def dump(self, digest: str) -> Optional[dict]:
        """One incident, serialized under the lock (see to_dicts)."""
        with self._lock:
            incident = self._entries.get(digest)
            return to_dict(incident) if incident is not None else None

    # -- ConfigMap snapshot -------------------------------------------
    def snapshot(self, max_bytes: int = MAX_SNAPSHOT_BYTES) -> str:
        """Newest-first JSONL of the store, truncated (whole incidents at
        a time, oldest dropped first) to fit ``max_bytes`` of UTF-8 —
        bytes because that is what the apiserver's 1 MiB object limit
        counts (non-ASCII evidence encodes at 3-4 bytes per char)."""
        lines: list[str] = []
        used = 0
        for payload in self.to_dicts(newest_first=True):  # lock-held to_dict
            line = json.dumps({"op": "put", "incident": payload}, sort_keys=True)
            cost = len(line.encode("utf-8")) + 1
            if used + cost > max_bytes:
                break
            lines.append(line)
            used += cost
        return "\n".join(lines)

    def load_snapshot(self, text: str) -> int:
        """Merge a snapshot produced by :meth:`snapshot` (e.g. read back
        from the ConfigMap after a restart without a PVC).  Existing
        entries win — the journal is fresher than any snapshot."""
        loaded = 0
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                incident = Incident.parse(json.loads(line)["incident"])
            except (ValueError, KeyError, TypeError):
                continue
            if not incident.fingerprint:
                continue
            with self._lock:
                if incident.fingerprint in self._entries:
                    continue
                self._entries[incident.fingerprint] = incident
                self._entries.move_to_end(incident.fingerprint, last=False)
                loaded += 1
        return loaded

    def digests(self) -> list[str]:
        with self._lock:
            return list(self._entries.keys())

    def iter_incidents(self) -> Iterable[Incident]:
        return self.all(newest_first=False)
