"""Stable failure fingerprints — the identity key of incident memory.

A fleet replays the same failure classes endlessly: the 500th
CrashLoopBackOff of one bad deploy differs from the 1st only in pod-name
suffix, timestamps, and request ids.  The fingerprint collapses those
instances onto one key so the pipeline can recognise "seen this before"
(memory/recall.py) instead of paying the full pattern-match + TPU decode
cost again.

Identity basis (everything else is deliberately excluded):

- the set of matched pattern ids (sorted — match order is scheduling noise);
- the container exit code and termination/waiting reason from the pod's
  status (the reference detects these, PodFailureWatcher.java:147-159);
- a NORMALIZED template of the strongest evidence lines: timestamps, hex
  ids, UUIDs, IPs, digit runs, and pod-name hash suffixes are replaced by
  placeholder tokens, so two pods of one ReplicaSet crashing a minute
  apart produce byte-identical templates.

Pod name/namespace are NOT part of the identity: the whole point is that
`web-1` and `web-2` failing the same way share one incident.
"""

from __future__ import annotations

import hashlib
import json
import re
from dataclasses import dataclass
from typing import Optional

from ..schema.analysis import AnalysisResult
from ..schema.kube import Pod

#: evidence lines folded into the template (matches the prompt's top-3
#: evidence selection, serving/prompts.py — the lines a human would read)
TEMPLATE_EVENTS = 3

# Normalisation rules, applied IN ORDER (earlier rules must not produce
# text a later rule would mangle differently across runs).  Each replaces
# run-specific noise with a stable placeholder.
_RULES: list[tuple[re.Pattern, str]] = [
    # RFC3339 / ISO-8601 timestamps, with or without T/offset/fraction
    (re.compile(r"\b\d{4}-\d{2}-\d{2}[T ]\d{2}:\d{2}:\d{2}(?:\.\d+)?(?:Z|[+-]\d{2}:?\d{2})?\b"), "<ts>"),
    # bare dates and clock times (log prefixes like "2026-01-01" / "09:14:03,123")
    (re.compile(r"\b\d{4}-\d{2}-\d{2}\b"), "<date>"),
    (re.compile(r"\b\d{2}:\d{2}:\d{2}(?:[.,]\d+)?\b"), "<time>"),
    # UUIDs before the generic hex rule eats their segments
    (re.compile(r"\b[0-9a-fA-F]{8}-[0-9a-fA-F]{4}-[0-9a-fA-F]{4}-[0-9a-fA-F]{4}-[0-9a-fA-F]{12}\b"), "<uuid>"),
    # IPv4 (optionally with :port)
    (re.compile(r"\b\d{1,3}(?:\.\d{1,3}){3}(?::\d+)?\b"), "<ip>"),
    # 0x-prefixed and long bare hex (addresses, request ids, image digests)
    (re.compile(r"\b0x[0-9a-fA-F]+\b"), "<hex>"),
    (re.compile(r"\b[0-9a-f]{8,}\b"), "<hex>"),
    # kubernetes name hash suffixes: "-7f9c" / "-x2b9z" style trailing
    # segments that contain a digit (ReplicaSet/pod suffixes) — a plain
    # word like "half-open" has no digit and survives
    (re.compile(r"-(?=[a-z0-9]{4,10}\b)(?=[a-z]*\d)[a-z0-9]{4,10}\b"), "-<id>"),
    # any remaining digit run (ports, counts, durations, pids)
    (re.compile(r"\d+"), "<n>"),
]

_WS = re.compile(r"[ \t]+")


def normalize_line(line: str) -> str:
    """One evidence line with its run-specific noise replaced by
    placeholders; idempotent (normalize(normalize(x)) == normalize(x))."""
    out = line.strip()
    for pattern, token in _RULES:
        out = pattern.sub(token, out)
    return _WS.sub(" ", out)


def evidence_template(result: Optional[AnalysisResult]) -> str:
    """The normalized template of the strongest evidence lines (matched
    line per top event — the context around it is presentation, not
    identity), deduplicated preserving order."""
    if result is None:
        return ""
    lines: list[str] = []
    for event in result.top_events(TEMPLATE_EVENTS):
        if event.context is None or not event.context.matched_line:
            continue
        normalized = normalize_line(event.context.matched_line)
        if normalized and normalized not in lines:
            lines.append(normalized)
    return "\n".join(lines)


def _termination_identity(pod: Optional[Pod]) -> tuple[Optional[int], Optional[str]]:
    """(exit code, reason) of the failing container: the terminated state's
    exit code/reason when present, else the waiting reason
    (CrashLoopBackOff, ImagePullBackOff...)."""
    if pod is None or pod.status is None:
        return None, None
    exit_code: Optional[int] = None
    reason: Optional[str] = None
    for cs in [*pod.status.container_statuses, *pod.status.init_container_statuses]:
        for state in (cs.state, cs.last_state):
            if state is None:
                continue
            if state.terminated is not None:
                if exit_code is None:
                    exit_code = state.terminated.exit_code
                if reason is None and state.terminated.reason:
                    reason = state.terminated.reason
            if state.waiting is not None and reason is None and state.waiting.reason:
                reason = state.waiting.reason
    return exit_code, reason


def incident_embedding_text(
    template: str,
    pattern_ids: "tuple[str, ...] | list[str]",
    reason: Optional[str],
    exit_code: Optional[int],
) -> str:
    """THE canonical embedding basis for near-miss scoring — used both at
    insert time (FailureFingerprint.embedding_text) and when the index is
    rebuilt from stored incidents (memory/index.py), so a restart can
    never shift near-miss scores."""
    parts = [template, *pattern_ids]
    if reason:
        parts.append(reason)
    if exit_code is not None:
        parts.append(f"exit {exit_code}")
    return " ".join(p for p in parts if p)


@dataclass(frozen=True)
class FailureFingerprint:
    """The stable identity of one failure class.  ``digest`` is the store
    key; the components ride along for display and for the embedding text
    the near-miss index scores."""

    digest: str
    pattern_ids: tuple[str, ...] = ()
    exit_code: Optional[int] = None
    reason: Optional[str] = None
    template: str = ""

    @property
    def is_weak(self) -> bool:
        """True when the identity basis is only (exit code, reason) — no
        matched patterns, no evidence template.  Two UNRELATED apps both
        dying with exit 1 would collide on such a digest, so weak
        fingerprints are never stored or reused (memory/recall.py): a
        wrong-but-confident recycled root cause is worse than a cold
        analysis."""
        return not self.pattern_ids and not self.template

    def embedding_text(self) -> str:
        """What the incident index embeds for near-miss scoring: the
        template plus the identity fields, so lexically different phrasings
        of one failure class still land close."""
        return incident_embedding_text(
            self.template, self.pattern_ids, self.reason, self.exit_code
        )

    def short(self) -> str:
        return self.digest[:12]


def failure_fingerprint(
    result: Optional[AnalysisResult], pod: Optional[Pod] = None
) -> FailureFingerprint:
    """Fingerprint one analyzed failure.  Deterministic: equal inputs (up
    to the normalized noise) yield byte-equal digests across processes."""
    pattern_ids = tuple(sorted({
        event.matched_pattern.id
        for event in (result.events if result else [])
        if event.matched_pattern is not None and event.matched_pattern.id
    }))
    exit_code, reason = _termination_identity(pod)
    template = evidence_template(result)
    basis = json.dumps(
        {
            "patterns": list(pattern_ids),
            "exit": exit_code,
            "reason": reason,
            "template": template,
        },
        sort_keys=True,
    )
    return FailureFingerprint(
        digest=hashlib.sha256(basis.encode()).hexdigest(),
        pattern_ids=pattern_ids,
        exit_code=exit_code,
        reason=reason,
        template=template,
    )
