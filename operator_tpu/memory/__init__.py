"""Incident memory: failure fingerprinting, a durable incident store, a
TPU-scored embedding index, and the recall policy that lets the analysis
pipeline reuse whole analyses for recurring failures.

See docs/MEMORY.md for the fingerprint spec, recall policy, and tuning.
"""

from .fingerprint import FailureFingerprint, evidence_template, failure_fingerprint, normalize_line
from .index import IncidentIndex
from .recall import (
    RECALL_HIT,
    RECALL_MISS,
    RECALL_NEAR,
    IncidentMemory,
    RecallDecision,
    build_incident_memory,
)
from .store import Incident, IncidentStore

__all__ = [
    "FailureFingerprint",
    "Incident",
    "IncidentIndex",
    "IncidentMemory",
    "IncidentStore",
    "RECALL_HIT",
    "RECALL_MISS",
    "RECALL_NEAR",
    "RecallDecision",
    "build_incident_memory",
    "evidence_template",
    "failure_fingerprint",
    "normalize_line",
]
