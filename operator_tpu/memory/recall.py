"""Recall policy — what incident memory does to the analysis hot path.

Three outcomes per analyzed failure (operator/pipeline.py consults this
between the pattern parse and the AI leg):

- **hit** — the exact fingerprint is stored with a reusable analysis: the
  pipeline reuses it verbatim and skips the AI leg entirely.  A recurring
  fleet-wide failure turns from a multi-second TPU decode into a store
  lookup, and the analysis's unused deadline budget is returned.
- **near** — no exact hit, but stored incidents score above the embedder's
  similarity threshold: the top-k priors are injected into the prompt as
  retrieval-augmented context (serving/prompts.py) and linked on the new
  incident.
- **miss** — full analysis; the result is inserted afterwards.

Counters: ``podmortem_recall_{hit,near,miss}_total`` on ``/metrics``.
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass, field
from typing import Optional

from ..patterns.semantic import Embedder, HashingEmbedder
from ..schema.analysis import AIResponse, AnalysisResult
from ..schema.kube import Pod
from ..schema.meta import now_iso
from .fingerprint import FailureFingerprint, failure_fingerprint
from .index import IncidentIndex
from .store import CachedAnalysis, Incident, IncidentStore

log = logging.getLogger(__name__)

RECALL_HIT = "hit"
RECALL_NEAR = "near"
RECALL_MISS = "miss"

#: ConfigMap data key holding the snapshot JSONL
CONFIGMAP_KEY = "incidents"


@dataclass
class RecallDecision:
    kind: str  # RECALL_HIT | RECALL_NEAR | RECALL_MISS
    fingerprint: FailureFingerprint
    #: the stored incident for this exact fingerprint (post recurrence
    #: bump) — present on hit, and on near/miss when the class was seen
    #: before without a reusable analysis
    incident: Optional[Incident] = None
    #: on hit: the recalling CR's OWN cached analysis (per provider ref)
    analysis: Optional[CachedAnalysis] = None
    #: (prior incident, similarity score) pairs for prompt injection,
    #: best first — non-empty only on near
    neighbors: list[tuple[Incident, float]] = field(default_factory=list)
    #: flight-recorder trace id of the PREVIOUS sighting's analysis
    #: (captured before this sighting overwrote it) — how a recurrence
    #: links back to its prior timeline (docs/OBSERVABILITY.md)
    prior_trace_id: Optional[str] = None


class IncidentMemory:
    """Fingerprint + store + index composed behind the pipeline's API."""

    def __init__(
        self,
        store: Optional[IncidentStore] = None,
        index: Optional[IncidentIndex] = None,
        embedder: Optional[Embedder] = None,
        *,
        near_threshold: Optional[float] = None,
        top_k: int = 3,
        configmap: Optional[str] = None,
        flush_interval_s: float = 30.0,
        kube_timeout_s: float = 15.0,
    ) -> None:
        embedder = embedder or HashingEmbedder()
        # explicit None checks: an EMPTY store/index is falsy (__len__) and
        # must not be swapped for a fresh default
        self.store = store if store is not None else IncidentStore()
        self.index = index if index is not None else IncidentIndex(embedder)
        # threshold is an embedder property (lexical overlap scores run
        # lower than contextual cosines), overridable by config
        self.near_threshold = (
            near_threshold
            if near_threshold is not None and near_threshold > 0
            else getattr(self.index.embedder, "default_threshold", 0.3)
        )
        self.top_k = max(1, top_k)
        self.configmap = configmap
        self.flush_interval_s = flush_interval_s
        #: per-call budget for the ConfigMap snapshot/restore kube calls
        #: (mirrors OperatorConfig.kube_call_timeout_s): the flush rides
        #: the analysis pipeline's remember stage, and a wedged apiserver
        #: connection must cost one bounded attempt, not the analysis task
        self.kube_timeout_s = kube_timeout_s
        self._last_flush = 0.0
        if len(self.store):
            # journal-restored incidents must be queryable immediately
            self.index.rebuild(self.store.all(newest_first=False))

    # ------------------------------------------------------------------
    def recall(
        self,
        result: Optional[AnalysisResult],
        pod: Optional[Pod],
        *,
        allow_reuse: bool = True,
        provider_ref: Optional[str] = None,
        trace_id: Optional[str] = None,
    ) -> RecallDecision:
        """Classify one analyzed failure against memory.  Every call is a
        sighting: an exact fingerprint match bumps the incident's
        recurrence counters whether or not its analysis is reused.
        ``allow_reuse=False`` (no AI leg configured for this CR) still
        tracks recurrence but never returns a hit.  ``provider_ref``
        (the CR's "namespace/name" AIProvider reference) must equal the
        stored incident's — a hit must reuse an analysis the recalling CR
        would itself have produced, never another provider's text."""
        fingerprint = failure_fingerprint(result, pod)
        if fingerprint.is_weak:
            # (exit code, reason) alone is not an identity: unrelated
            # failures would collide and swap analyses — always analyze
            return RecallDecision(RECALL_MISS, fingerprint)
        # TTL sweep rides every recall, so a hit-only workload still ages
        # dead incidents out of the store AND the index
        expired = self.store.expire()
        if expired:
            self.index.remove(expired)
        incident = self.store.get(fingerprint.digest)
        prior_trace_id: Optional[str] = None
        if incident is not None:
            # the PRIOR sighting's trace, read before this sighting's
            # trace id overwrites it on the stored incident
            prior_trace_id = incident.last_trace_id
            # reuse is per provider ref: this CR only ever gets back an
            # analysis ITS OWN provider produced earlier
            cached = incident.analyses.get(provider_ref or "")
            reuse = (
                allow_reuse and cached is not None and bool(cached.explanation)
            )
            incident = self.store.record_recurrence(
                fingerprint.digest, reused=reuse, trace_id=trace_id
            )
            # incident is None only if eviction raced the lookup — fall
            # through to near/miss rather than reuse a vanished record
            if reuse and incident is not None:
                return RecallDecision(
                    RECALL_HIT, fingerprint, incident=incident, analysis=cached,
                    prior_trace_id=prior_trace_id,
                )
        neighbors: list[tuple[Incident, float]] = []
        for digest, score in self.index.query(
            fingerprint.embedding_text(), k=self.top_k + 1
        ):
            if digest == fingerprint.digest or score < self.near_threshold:
                continue
            prior = self.store.get(digest)
            if prior is None or not prior.explanation:
                continue  # nothing worth injecting
            neighbors.append((prior, score))
        neighbors = neighbors[: self.top_k]
        if neighbors:
            return RecallDecision(
                RECALL_NEAR, fingerprint, incident=incident, neighbors=neighbors,
                prior_trace_id=prior_trace_id,
            )
        return RecallDecision(
            RECALL_MISS, fingerprint, incident=incident,
            prior_trace_id=prior_trace_id,
        )

    # ------------------------------------------------------------------
    @staticmethod
    def hit_probability(decision: RecallDecision) -> float:
        """How likely this request resolves from memory instead of a cold
        analysis — the admission signal the overload value model reads
        (router/value.py: a recall hit costs ~4% of a cold analysis, so
        a likely-recalled request is ~25x cheaper per unit value and is
        shed only after all cold work of equal-or-lower class).

        Pure read over an already-made decision: a hit IS a reuse (1.0);
        a known incident that could not be reused this time (no cached
        explanation for this provider ref, reuse disabled) still predicts
        a warm path (0.75); a near-neighbor match predicts partial reuse
        capped by the top neighbor's similarity (<= 0.5); a miss is cold
        (0.0)."""
        if decision.kind == RECALL_HIT:
            return 1.0
        if decision.kind == RECALL_NEAR:
            top = max((s for _, s in decision.neighbors), default=0.0)
            return min(0.5, float(top))
        if decision.incident is not None:
            return 0.75
        return 0.0

    # ------------------------------------------------------------------
    def insert(
        self,
        fingerprint: FailureFingerprint,
        result: Optional[AnalysisResult],
        pod: Optional[Pod],
        ai_response: Optional[AIResponse],
        *,
        related: Optional[list[str]] = None,
        seen_recorded: bool = False,
        provider_ref: Optional[str] = None,
        cacheable: bool = True,
        trace_id: Optional[str] = None,
    ) -> Optional[Incident]:
        """Remember a completed analysis (upsert: a class first seen
        pattern-only gains its analysis text when the AI leg later
        succeeds).  Returns the stored incident, or None for a weak
        fingerprint (see :meth:`FailureFingerprint.is_weak` — never
        stored).

        ``seen_recorded=True`` means this sighting's recurrence was
        already counted by :meth:`recall` (the digest was in the store
        then).  False + an existing digest = a concurrent first sighting
        (two pods of one ReplicaSet crashing together): the upsert bumps
        ``seen_count`` so the race cannot undercount recurrence.

        Only a CLEAN analysis is stored as reusable: an errored or
        deadline-truncated explanation would otherwise be replayed
        verbatim forever, freezing a cut-off root cause fleet-wide.
        ``cacheable=False`` (the AIProvider's cachingEnabled opt-out)
        tracks recurrence but never remembers the generated text."""
        if fingerprint.is_weak:
            return None
        reusable = (
            cacheable
            and ai_response is not None
            and bool(ai_response.explanation)
            and not ai_response.error
            and ai_response.deadline_outcome in (None, "completed")
        )
        now = now_iso()
        incident = Incident(
            fingerprint=fingerprint.digest,
            pattern_ids=list(fingerprint.pattern_ids),
            severity=(result.summary.highest_severity if result else None),
            template=fingerprint.template,
            exit_code=fingerprint.exit_code,
            reason=fingerprint.reason,
            explanation=ai_response.explanation if reusable else None,
            provider_id=(ai_response.provider_id if ai_response else None),
            model_id=(ai_response.model_id if ai_response else None),
            analyses=(
                {
                    provider_ref or "": CachedAnalysis(
                        explanation=ai_response.explanation,
                        provider_id=ai_response.provider_id,
                        model_id=ai_response.model_id,
                    )
                }
                if reusable
                else {}
            ),
            pod_name=(pod.metadata.name if pod else None),
            pod_namespace=(pod.metadata.namespace if pod else None),
            first_seen=now,
            last_seen=now,
            related=list(related or []),
            last_trace_id=trace_id,
        )
        evicted = self.store.upsert(incident, bump_if_existing=not seen_recorded)
        if evicted:
            self.index.remove(evicted)
        stored = self.store.get(fingerprint.digest)
        assert stored is not None
        self.index.add(stored)
        return stored

    # ------------------------------------------------------------------
    def query_text(self, text: str, k: int = 3) -> list[tuple[Incident, float]]:
        """Free-text similarity query (the /incidents/query endpoint):
        top-k stored incidents by embedding score, no threshold — a
        debugging surface, the caller reads the scores."""
        out: list[tuple[Incident, float]] = []
        for digest, score in self.index.query(text, k=k):
            incident = self.store.get(digest)
            if incident is not None:
                out.append((incident, score))
        return out

    def close(self) -> None:
        self.store.close()

    # -- ConfigMap backing ---------------------------------------------
    async def restore_from_configmap(self, api, namespace: str) -> int:
        """Merge the ConfigMap snapshot into the store (PVC-less restarts).
        Journal/live entries win over snapshot ones."""
        if not self.configmap:
            return 0
        from ..operator.kubeapi import ApiError, NotFoundError  # lazy: no cycle

        try:
            cm = await asyncio.wait_for(
                api.get("ConfigMap", self.configmap, namespace),
                timeout=self.kube_timeout_s,
            )
        except NotFoundError:
            return 0
        except (ApiError, asyncio.TimeoutError) as exc:
            log.warning("incident ConfigMap restore failed: %s",
                        str(exc) or "timed out")
            return 0
        loaded = self.store.load_snapshot((cm.get("data") or {}).get(CONFIGMAP_KEY, ""))
        if loaded:
            self.index.rebuild(self.store.all(newest_first=False))
            log.info("incident memory: %d incident(s) restored from ConfigMap %s",
                     loaded, self.configmap)
        return loaded

    async def maybe_flush_to_configmap(
        self, api, namespace: str, clock=None, *, force: bool = False
    ) -> bool:
        """Snapshot the store into the ConfigMap at most once per
        ``flush_interval_s`` (called after inserts; failures are logged,
        never raised — durability backing must not break analyses).
        ``force=True`` bypasses the throttle — the shutdown flush, so the
        last interval's incidents survive a PVC-less restart."""
        if not self.configmap:
            return False
        import time as _time

        now = (clock or _time.monotonic)()
        if (not force and self._last_flush
                and now - self._last_flush < self.flush_interval_s):
            return False
        from ..operator.kubeapi import ApiError, NotFoundError  # lazy: no cycle

        try:
            data = {CONFIGMAP_KEY: self.store.snapshot()}
            try:
                await asyncio.wait_for(
                    api.patch("ConfigMap", self.configmap, namespace,
                              {"data": data}),
                    timeout=self.kube_timeout_s,
                )
            except NotFoundError:
                await asyncio.wait_for(
                    api.create("ConfigMap", {
                        "apiVersion": "v1", "kind": "ConfigMap",
                        "metadata": {"name": self.configmap,
                                     "namespace": namespace},
                        "data": data,
                    }),
                    timeout=self.kube_timeout_s,
                )
            # advance the throttle only on SUCCESS: a transient apiserver
            # error must not suppress the retry for a whole interval
            self._last_flush = now
            return True
        except (ApiError, asyncio.TimeoutError) as exc:
            log.warning("incident ConfigMap flush failed: %s",
                        str(exc) or "timed out")
            return False


def build_incident_memory(config, *, embedder: Optional[Embedder] = None):
    """The one construction path (pipeline default + operator wiring):
    ``None`` when the subsystem is disabled.  ``embedder`` lets the
    operator share the semantic matcher's neural encoder; the default is
    the always-available lexical HashingEmbedder."""
    if not getattr(config, "memory_enabled", True):
        return None
    store = IncidentStore(
        config.memory_path or None,
        max_entries=config.memory_max_entries,
        ttl_s=config.memory_ttl_s,
    )
    return IncidentMemory(
        store=store,
        embedder=embedder,
        near_threshold=config.recall_threshold or None,
        top_k=config.recall_top_k,
        configmap=config.memory_configmap or None,
        flush_interval_s=config.memory_flush_interval_s,
        kube_timeout_s=getattr(config, "kube_call_timeout_s", 15.0),
    )
