"""Embedding index over stored incidents — the near-miss half of recall.

Exact fingerprint equality catches literal replays; this index catches the
*same failure phrased differently* (another service, another JVM version,
another log format for one root cause).  It reuses the pattern engine's
embedder ladder (patterns/semantic.py: lexical :class:`HashingEmbedder`
always, MiniLM-class :class:`NeuralEmbedder` when a checkpoint is mounted)
and scores query × incidents on the MXU via the fused best-window kernel
(ops/similarity.py) — one query row against the whole incident matrix is
exactly the ``windows @ patterns.T`` shape that kernel streams.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional, Sequence

import numpy as np

from ..patterns.semantic import Embedder, HashingEmbedder
from .store import Incident

log = logging.getLogger(__name__)


class IncidentIndex:
    """(digests, embedding matrix) kept in lockstep; readers snapshot the
    pair atomically (same discipline as SemanticMatcher._state)."""

    def __init__(self, embedder: Optional[Embedder] = None) -> None:
        self.embedder = embedder or HashingEmbedder()
        self._lock = threading.Lock()
        self._state: tuple[list[str], np.ndarray] = (
            [],
            np.zeros((0, self.embedder.dim), np.float32),
        )

    def __len__(self) -> int:
        # graftlint: disable=GL004 reason=deliberate lock-free snapshot read; _state is an immutable tuple swapped atomically under the lock
        return len(self._state[0])

    # ------------------------------------------------------------------
    def rebuild(self, incidents: Sequence[Incident], texts: Optional[Sequence[str]] = None) -> int:
        """Re-embed every incident (after eviction or a restore).  ``texts``
        overrides the per-incident embedding text when the caller has richer
        basis than the stored template (recall passes fingerprint
        embedding_text)."""
        digests = [i.fingerprint for i in incidents if i.fingerprint]
        if texts is None:
            texts = [self._incident_text(i) for i in incidents if i.fingerprint]
        embeddings = self.embedder.embed(list(texts))
        with self._lock:
            self._state = (digests, embeddings)
        return len(digests)

    def add(self, incident: Incident, text: Optional[str] = None) -> None:
        """Append one incident's embedding row (no-op if already present —
        an upsert of an existing digest keeps its original embedding, the
        template is part of the identity and cannot have changed)."""
        if not incident.fingerprint:
            return
        row = self.embedder.embed([text or self._incident_text(incident)])
        with self._lock:
            digests, matrix = self._state
            if incident.fingerprint in digests:
                return
            self._state = (
                digests + [incident.fingerprint],
                np.concatenate([matrix, row.astype(np.float32)], axis=0),
            )

    def remove(self, evicted: Sequence[str]) -> None:
        if not evicted:
            return
        gone = set(evicted)
        with self._lock:
            digests, matrix = self._state
            keep = [i for i, d in enumerate(digests) if d not in gone]
            self._state = (
                [digests[i] for i in keep],
                matrix[keep] if keep else np.zeros((0, self.embedder.dim), np.float32),
            )

    @staticmethod
    def _incident_text(incident: Incident) -> str:
        from .fingerprint import incident_embedding_text  # one shared basis

        return incident_embedding_text(
            incident.template, incident.pattern_ids,
            incident.reason, incident.exit_code,
        )

    # ------------------------------------------------------------------
    def query(self, text: str, k: int = 3) -> list[tuple[str, float]]:
        """Top-k (digest, cosine score), descending.  Scores on the MXU via
        the fused Pallas kernel on TPU, XLA/numpy elsewhere."""
        # graftlint: disable=GL004 reason=deliberate lock-free snapshot read; _state is an immutable tuple swapped atomically under the lock
        digests, matrix = self._state  # one consistent snapshot
        if not digests or not text.strip():
            return []
        query = self.embedder.embed([text]).astype(np.float32)  # [1, D]
        scores = self._score(query, matrix)
        k = min(max(1, k), len(digests))
        order = np.argsort(scores)[::-1][:k]
        return [(digests[int(i)], float(scores[int(i)])) for i in order]

    @staticmethod
    def _score(query: np.ndarray, matrix: np.ndarray) -> np.ndarray:
        try:
            import jax.numpy as jnp

            from ..ops.similarity import best_window_scores

            # one query "window" against the incident matrix as the
            # pattern side: per-incident best == the cosine itself
            scores, _ = best_window_scores(jnp.asarray(query), jnp.asarray(matrix))
            return np.asarray(scores)
        except Exception:  # pragma: no cover - numpy fallback if jax breaks
            log.debug("similarity op unavailable; numpy fallback", exc_info=True)
            return (matrix @ query[0]).astype(np.float32)
