"""Sharded training step — fine-tuning support for the explanation models.

The serving path is inference, but the framework carries a real training
loop so explanation models can be adapted on recorded failure/explanation
pairs (the reference has no equivalent; its models are frozen API calls).
The step is a single ``jax.jit`` over the mesh: batch sharded on (dp, fsdp),
params on (fsdp, tp) per ``mesh.param_specs`` — XLA emits the
reduce-scatter/all-gather pattern over ICI from the sharding constraints
alone (the scaling-book recipe; no hand-written collectives).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding

from ..models.configs import ModelConfig
from ..models.llama import Params, forward
from .mesh import batch_spec, param_shardings


@dataclass
class TrainState:
    params: Params
    opt_state: Any
    step: jax.Array


def next_token_loss(
    params: Params,
    config: ModelConfig,
    token_ids: jax.Array,  # [B, T]
    loss_mask: jax.Array,  # [B, T] 1.0 where the target counts
    *,
    lora: Any = None,  # adapter tree (parallel/lora.py); low-rank path only
    lora_alpha: float = 16.0,
) -> jax.Array:
    """Mean next-token cross-entropy (float32 logits; stable logsumexp)."""
    b, t = token_ids.shape
    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32)[None], (b, t))
    logits, _ = forward(
        params, config, token_ids, positions, lora=lora, lora_alpha=lora_alpha
    )
    targets = token_ids[:, 1:]
    logits = logits[:, :-1]
    mask = loss_mask[:, 1:].astype(jnp.float32)
    logprobs = jax.nn.log_softmax(logits, axis=-1)
    picked = jnp.take_along_axis(logprobs, targets[..., None].astype(jnp.int32), axis=-1)[..., 0]
    return -(picked * mask).sum() / jnp.maximum(mask.sum(), 1.0)


def make_optimizer(learning_rate: float = 1e-4, weight_decay: float = 0.01) -> optax.GradientTransformation:
    return optax.adamw(learning_rate, b1=0.9, b2=0.95, weight_decay=weight_decay)


def make_train_step(
    config: ModelConfig,
    mesh: Mesh,
    optimizer: Optional[optax.GradientTransformation] = None,
):
    """Returns (init_state, train_step) both jitted over the mesh."""
    optimizer = optimizer or make_optimizer()
    p_shardings = param_shardings(mesh, config)
    data_sharding = NamedSharding(mesh, batch_spec())

    def init_state(params: Params) -> TrainState:
        return TrainState(params=params, opt_state=optimizer.init(params),
                          step=jnp.zeros((), jnp.int32))

    @partial(
        jax.jit,
        in_shardings=(None, data_sharding, data_sharding),
        donate_argnums=(0,),
    )
    def train_step(state: TrainState, token_ids: jax.Array, loss_mask: jax.Array):
        loss, grads = jax.value_and_grad(next_token_loss)(
            state.params, config, token_ids, loss_mask
        )
        updates, new_opt = optimizer.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        # keep the placement stable across steps
        new_params = jax.lax.with_sharding_constraint(new_params, p_shardings)
        return TrainState(new_params, new_opt, state.step + 1), loss

    return init_state, train_step


jax.tree_util.register_pytree_node(
    TrainState,
    lambda s: ((s.params, s.opt_state, s.step), None),
    lambda _, c: TrainState(params=c[0], opt_state=c[1], step=c[2]),
)
