"""Sharded training step — fine-tuning support for the explanation models.

The serving path is inference, but the framework carries a real training
loop so explanation models can be adapted on recorded failure/explanation
pairs (the reference has no equivalent; its models are frozen API calls).
The step is a single ``jax.jit`` over the mesh: batch sharded on (dp, fsdp),
params on (fsdp, tp) per ``mesh.param_specs`` — XLA emits the
reduce-scatter/all-gather pattern over ICI from the sharding constraints
alone (the scaling-book recipe; no hand-written collectives).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding

from ..models.configs import ModelConfig
from ..models.llama import Params, forward
from .mesh import batch_spec, param_shardings


@dataclass
class TrainState:
    params: Params
    opt_state: Any
    step: jax.Array


def next_token_loss(
    params: Params,
    config: ModelConfig,
    token_ids: jax.Array,  # [B, T]
    loss_mask: jax.Array,  # [B, T] 1.0 where the target counts
    *,
    lora: Any = None,  # adapter tree (parallel/lora.py); low-rank path only
    lora_alpha: float = 16.0,
) -> jax.Array:
    """Mean next-token cross-entropy (float32 logits; stable logsumexp)."""
    b, t = token_ids.shape
    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32)[None], (b, t))
    logits, _ = forward(
        params, config, token_ids, positions, lora=lora, lora_alpha=lora_alpha
    )
    targets = token_ids[:, 1:]
    logits = logits[:, :-1]
    mask = loss_mask[:, 1:].astype(jnp.float32)
    logprobs = jax.nn.log_softmax(logits, axis=-1)
    picked = jnp.take_along_axis(logprobs, targets[..., None].astype(jnp.int32), axis=-1)[..., 0]
    return -(picked * mask).sum() / jnp.maximum(mask.sum(), 1.0)


def make_optimizer(learning_rate: float = 1e-4, weight_decay: float = 0.01) -> optax.GradientTransformation:
    return optax.adamw(learning_rate, b1=0.9, b2=0.95, weight_decay=weight_decay)


def make_train_step(
    config: ModelConfig,
    mesh: Mesh,
    optimizer: Optional[optax.GradientTransformation] = None,
):
    """Returns (init_state, train_step) both jitted over the mesh."""
    optimizer = optimizer or make_optimizer()
    p_shardings = param_shardings(mesh, config)
    data_sharding = NamedSharding(mesh, batch_spec())

    def constrain_opt(opt_state):
        """Pin the adam moments to the PARAM placements: left to
        propagation, XLA may replicate mu/nu — 2x the weight memory on
        every device, an OOM at 8B scale — and init/step programs may
        pick different layouts (resharding each step)."""
        def pin(tree):
            return jax.tree.map(
                lambda x, sh: jax.lax.with_sharding_constraint(x, sh),
                tree, p_shardings,
            )
        constrained = []
        for part in opt_state:
            if isinstance(part, optax.ScaleByAdamState):
                part = part._replace(mu=pin(part.mu), nu=pin(part.nu))
            constrained.append(part)
        return tuple(constrained)

    @jax.jit
    def init_state(params: Params) -> TrainState:
        # jitted so the optimizer moments inherit the params' MESH
        # placement: a plain optimizer.init materialises both full moment
        # trees on one device — an OOM at 8B scale, and committed
        # single-device scalars that conflict with mesh-placed leaves on
        # the next step (seen via checkpoint restore)
        return TrainState(params=params,
                          opt_state=constrain_opt(optimizer.init(params)),
                          step=jnp.zeros((), jnp.int32))

    @partial(
        jax.jit,
        in_shardings=(None, data_sharding, data_sharding),
        donate_argnums=(0,),
    )
    def train_step(state: TrainState, token_ids: jax.Array, loss_mask: jax.Array):
        loss, grads = jax.value_and_grad(next_token_loss)(
            state.params, config, token_ids, loss_mask
        )
        updates, new_opt = optimizer.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        # keep the placement stable across steps (params AND moments)
        new_params = jax.lax.with_sharding_constraint(new_params, p_shardings)
        return TrainState(new_params, constrain_opt(new_opt), state.step + 1), loss

    return init_state, train_step


def save_train_state(state: TrainState, path: str) -> None:
    """Durable fine-tune checkpoint (params + optimizer state + step) via
    orbax — the resume story for the training flows, alongside the
    HF-layout weight save (models/loader.save_params) that serving
    reloads.  Works for sharded states: orbax records each leaf's
    sharding and restore re-places onto the same mesh layout."""
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as checkpointer:
        # force: the resume story saves to a fixed path every N steps —
        # the default raises on an existing destination
        checkpointer.save(os.path.abspath(path), state, force=True)
        checkpointer.wait_until_finished()


def load_train_state(path: str, reference: TrainState) -> TrainState:
    """Restore a checkpoint saved by :func:`save_train_state`.

    ``reference`` supplies the tree structure, dtypes, and TARGET
    shardings (e.g. a fresh ``init_state(params)`` on the current mesh) —
    restore places every leaf straight onto the reference's devices, so
    resuming on a different mesh factorisation just means passing a
    reference built on the new mesh."""
    import orbax.checkpoint as ocp

    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(
            jnp.shape(x), jnp.asarray(x).dtype,
            sharding=getattr(x, "sharding", None),
        ),
        reference,
    )
    with ocp.StandardCheckpointer() as checkpointer:
        return checkpointer.restore(os.path.abspath(path), abstract)


jax.tree_util.register_pytree_node(
    TrainState,
    lambda s: ((s.params, s.opt_state, s.step), None),
    lambda _, c: TrainState(params=c[0], opt_state=c[1], step=c[2]),
)
