"""Mesh / sharding layer (SURVEY.md §2.3, §7 stage 4): DP + TP + FSDP over
ICI via jax.sharding, multi-host over DCN via jax.distributed."""

from .mesh import (
    AXES,
    MeshPlan,
    batch_spec,
    initialize_distributed,
    kv_cache_spec,
    logits_spec,
    make_mesh,
    mesh_summary,
    paged_cache_specs,
    param_shardings,
    param_specs,
    plan_for,
    shard_params,
    validate_param_shardings,
)
from .lora import (
    apply_lora,
    init_lora,
    load_lora,
    lora_param_count,
    lora_shardings,
    make_lora_train_step,
    merge_lora,
    save_lora,
    stack_adapters,
    zero_lora,
)
from .train import (
    TrainState,
    load_train_state,
    make_optimizer,
    make_train_step,
    next_token_loss,
    save_train_state,
)

__all__ = [name for name in dir() if not name.startswith("_")]
