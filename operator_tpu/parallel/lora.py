"""LoRA adapters: low-rank fine-tuning of the explanation models.

Full fine-tuning an 8B model needs optimizer state for every weight —
3x the parameter bytes in f32 moments, far beyond one v5e chip.  LoRA
trains only rank-r factors per projection:

    W_eff = W + (alpha / r) * A @ B      A: [in, r]   B: [r, out]

so the trainable state at 8B/rank-16 is ~50 MB instead of ~90 GB, and the
frozen base weights stay int8/bf16 on device.  Adapters follow the stacked
``[n_layers, ...]`` layout of models/llama.py and shard over the same mesh
axes as their base matrix (A takes the base fan-in axis, B the fan-out
axis — derived per matrix in :func:`lora_specs`, so row-parallel wo/w_down
get the transposed layout), and XLA's collectives match the base model's.

TRAINING never materialises a delta matrix: the low-rank path ``x @ A @ B``
is added inside the model's projections (models/llama.py ``forward(lora=)``)
so gradients exist for the rank-r factors alone.  SERVING merges once at
load (:func:`merge_lora` / :func:`apply_lora`) — a load-time operation
whose full-size f32 delta transients are acceptable there, with zero
runtime overhead afterwards.

The reference has no training of any kind (SURVEY.md §2: frozen API
calls); this is the tpu-native "adapt the explanation model on recorded
failure/explanation pairs" flow the rebuild adds.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.configs import ModelConfig
from ..models.llama import Params, layer_matrix_shapes
from .mesh import batch_spec, param_shardings
from .train import TrainState, make_optimizer, next_token_loss

#: default adaptation targets: attention in/out projections — the standard
#: LoRA placement; add mlp names for higher-capacity adaptation
DEFAULT_TARGETS = ("wq", "wk", "wv", "wo")

LoraParams = dict[str, dict[str, jax.Array]]


def init_lora(
    config: ModelConfig,
    key: jax.Array,
    *,
    rank: int = 16,
    targets: Sequence[str] = DEFAULT_TARGETS,
    dtype: jnp.dtype = jnp.bfloat16,
) -> LoraParams:
    """A ~ N(0, 1/r) and B = 0, so W_eff == W at step 0 (standard LoRA)."""
    shapes = layer_matrix_shapes(config)
    unknown = set(targets) - set(shapes)
    assert not unknown, f"unknown LoRA targets {unknown}"
    adapters: LoraParams = {}
    for name, sub in zip(targets, jax.random.split(key, len(targets))):
        n, fan_in, fan_out = shapes[name]
        adapters[name] = {
            "a": (jax.random.normal(sub, (n, fan_in, rank), jnp.float32)
                  * rank**-0.5).astype(dtype),
            "b": jnp.zeros((n, rank, fan_out), dtype),
        }
    return adapters


def lora_param_count(adapters: LoraParams) -> int:
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(adapters))


def _delta(adapter: dict[str, jax.Array], alpha: float, rank: int) -> jax.Array:
    scale = alpha / rank
    return jnp.einsum(
        "nir,nro->nio", adapter["a"].astype(jnp.float32),
        adapter["b"].astype(jnp.float32),
    ) * scale


def apply_lora(
    params: Params, adapters: LoraParams, *, alpha: float = 16.0
) -> Params:
    """Merged params for SERVING (a load-time operation: the full-size f32
    delta transients are fine once, not per train step — training threads
    the factors through ``forward(lora=...)`` instead).  Quantized base
    matrices dequantize, merge, and stay float — merging into int8 would
    quantize the delta away at small ranks."""
    layers = dict(params["layers"])
    for name, adapter in adapters.items():
        base = layers[name]
        rank = adapter["a"].shape[-1]
        delta = _delta(adapter, alpha, rank)
        if isinstance(base, dict):  # quantized {q, s}
            dequant = base["q"].astype(jnp.float32) * base["s"][:, None, :]
            layers[name] = (dequant + delta).astype(adapter["a"].dtype)
        else:
            layers[name] = (base.astype(jnp.float32) + delta).astype(base.dtype)
    return {**params, "layers": layers}


def merge_lora(
    params: Params, adapters: LoraParams, *, alpha: float = 16.0
) -> Params:
    """Eager merge for serving (one jit per adapted matrix group)."""
    merge = jax.jit(partial(apply_lora, alpha=alpha))
    return jax.block_until_ready(merge(params, adapters))


def zero_lora(
    config: ModelConfig,
    *,
    rank: int = 16,
    targets: Sequence[str] = DEFAULT_TARGETS,
    dtype: jnp.dtype = jnp.bfloat16,
) -> LoraParams:
    """An all-zeros adapter: W_eff == W exactly.  Slot 0 of every stacked
    multi-adapter batch, so un-adapted requests ride the same program."""
    shapes = layer_matrix_shapes(config)
    return {
        name: {
            "a": jnp.zeros((shapes[name][0], shapes[name][1], rank), dtype),
            "b": jnp.zeros((shapes[name][0], rank, shapes[name][2]), dtype),
        }
        for name in targets
    }


def stack_adapters(adapters: Sequence[LoraParams]) -> LoraParams:
    """Stack adapters for per-request serving: each leaf becomes
    ``[n_layers, n_adapters, ...]`` — the LAYER axis stays leading so the
    model's layer scan slices it, handing the per-layer ``[n_adapters, ...]``
    factors to the per-slot gather (models/llama.py ``lora_indices``).

    All adapters must share targets and rank (one compiled program serves
    the whole set; pad ranks up-front if they differ).
    """
    if not adapters:
        raise ValueError("need at least one adapter")
    first = adapters[0]
    for other in adapters[1:]:
        if set(other) != set(first):
            raise ValueError(
                f"adapters must share targets: {sorted(other)} vs {sorted(first)}"
            )
        for name in first:
            for factor in ("a", "b"):
                if other[name][factor].shape != first[name][factor].shape:
                    raise ValueError(
                        f"adapter rank/shape mismatch on {name}.{factor}: "
                        f"{other[name][factor].shape} vs "
                        f"{first[name][factor].shape}"
                    )
    return {
        name: {
            factor: jnp.stack([ad[name][factor] for ad in adapters], axis=1)
            for factor in ("a", "b")
        }
        for name in first
    }


def save_lora(adapters: LoraParams, path: str) -> None:
    """Write an adapter as one safetensors file (``lora.{target}.{a|b}``)."""
    import os

    import numpy as np
    from safetensors.numpy import save_file

    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    flat = {}
    for name, factors in adapters.items():
        for factor, value in factors.items():
            flat[f"lora.{name}.{factor}"] = np.asarray(value)
    save_file(flat, path)


def load_lora(path: str, dtype: jnp.dtype = jnp.bfloat16) -> LoraParams:
    from safetensors.numpy import load_file

    adapters: LoraParams = {}
    for key, value in load_file(path).items():
        parts = key.split(".")
        if len(parts) != 3 or parts[0] != "lora" or parts[2] not in ("a", "b"):
            raise ValueError(f"not a LoRA adapter file: unexpected key {key!r}")
        adapters.setdefault(parts[1], {})[parts[2]] = jnp.asarray(value, dtype)
    for name, factors in adapters.items():
        if set(factors) != {"a", "b"}:
            raise ValueError(f"adapter target {name!r} is missing a factor")
    return adapters


def lora_specs(config: ModelConfig, targets: Sequence[str]) -> Any:
    """PartitionSpecs for adapter factors, DERIVED from each base matrix's
    spec (mesh.param_specs): A takes the base fan-in axis, B the base
    fan-out axis — so column-parallel wq/wk/wv (in on fsdp, out on tp) and
    row-parallel wo/w_down (in on tp, out on fsdp) both merge without any
    resharding of a full-size matrix."""
    from .mesh import param_specs

    base = param_specs(config)["layers"]  # plain (unquantized) matrix specs
    out = {}
    for name in targets:
        spec = base[name]
        out[name] = {
            "a": P(None, spec[1], None),  # [n, in, r]
            "b": P(None, None, spec[2]),  # [n, r, out]
        }
    return out


def lora_shardings(mesh: Mesh, adapters: LoraParams, config: ModelConfig) -> Any:
    specs = lora_specs(config, tuple(adapters))
    return jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec), specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def make_lora_train_step(
    config: ModelConfig,
    mesh: Mesh,
    *,
    alpha: float = 16.0,
    targets: Sequence[str] = DEFAULT_TARGETS,
    quantized_base: bool = False,
    optimizer: Optional[optax.GradientTransformation] = None,
):
    """Returns (init_state, train_step): trains ONLY the adapters.

    The forward threads the rank-r factors through ``forward(lora=...)``
    (models/llama.py) — no delta matrix, no full-rank gradients — so
    trainable memory is the factors plus their optimizer moments.  The
    frozen base rides along as a jit constant input (``quantized_base``
    selects the int8 {q, s} sharding tree).  Adapters are pinned to
    :func:`lora_specs` placements every step, mirroring train.py's
    ``with_sharding_constraint`` discipline.
    """
    optimizer = optimizer or make_optimizer()
    p_shardings = param_shardings(mesh, config, quantized=quantized_base)
    data_sharding = NamedSharding(mesh, batch_spec())
    adapter_shardings = lora_shardings(mesh, dict.fromkeys(targets), config)

    def init_state(adapters: LoraParams) -> TrainState:
        assert set(adapters) == set(targets), (set(adapters), set(targets))
        adapters = jax.tree_util.tree_map(jax.device_put, adapters, adapter_shardings)
        return TrainState(params=adapters, opt_state=optimizer.init(adapters),
                          step=jnp.zeros((), jnp.int32))

    def loss_fn(adapters, base_params, token_ids, loss_mask):
        return next_token_loss(
            base_params, config, token_ids, loss_mask,
            lora=adapters, lora_alpha=alpha,
        )

    @partial(
        jax.jit,
        in_shardings=(None, p_shardings, data_sharding, data_sharding),
        donate_argnums=(0,),
    )
    def train_step(
        state: TrainState, base_params: Params,
        token_ids: jax.Array, loss_mask: jax.Array,
    ):
        loss, grads = jax.value_and_grad(loss_fn)(
            state.params, base_params, token_ids, loss_mask
        )
        updates, new_opt = optimizer.update(grads, state.opt_state, state.params)
        new_adapters = optax.apply_updates(state.params, updates)
        new_adapters = jax.lax.with_sharding_constraint(
            new_adapters, adapter_shardings
        )
        return TrainState(new_adapters, new_opt, state.step + 1), loss

    return init_state, train_step


__all__ = [
    "DEFAULT_TARGETS",
    "LoraParams",
    "apply_lora",
    "init_lora",
    "load_lora",
    "lora_param_count",
    "lora_shardings",
    "lora_specs",
    "make_lora_train_step",
    "merge_lora",
    "save_lora",
    "stack_adapters",
    "zero_lora",
]
