"""Device mesh + sharding rules — the ICI/DCN scaling layer.

The reference has no distributed compute at all (SURVEY.md §2.3); this module
is the tpu-native equivalent of the comm backend the rebuild must add:

- one ``jax.sharding.Mesh`` with named axes ``("dp", "fsdp", "tp")``:
  * **dp**   — data parallel over failure events (BASELINE config 5:
    Mistral-7B DP over a v5e-8's ICI);
  * **tp**   — tensor parallel within a pod (Llama-3-8B on v5e-4: heads and
    MLP columns split 4-way, XLA inserts the psum after the row-parallel
    projections);
  * **fsdp** — parameter sharding for training/fine-tune flows (LoRA-style
    adaptation of the explanation model) and for fitting larger checkpoints;
- multi-host: ``initialize_distributed()`` wraps ``jax.distributed`` so DCN
  topologies work with the same mesh axes (dp outermost over hosts, so
  cross-host traffic is gradient/batch-level, and tp stays inside a pod's
  ICI domain — the scaling-book layout).

Pipeline (pp), expert (ep) and ring/sequence (sp) axes are deliberately not
wired into the default mesh: at the 1B-8B scale this system serves, a v5e-8
fits every model with dp x tp alone (SURVEY.md §5 long-context: "ring/Ulysses
CP is not required at 8B scale").  Long-log scaling is handled by windowed
embedding scoring (operator_tpu.patterns) + prompt context selection instead
of sequence-parallel attention.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.configs import ModelConfig
from ..models.llama import Params

log = logging.getLogger(__name__)

AXES = ("dp", "fsdp", "tp")


@dataclass(frozen=True)
class MeshPlan:
    dp: int = 1
    fsdp: int = 1
    tp: int = 1

    @property
    def total(self) -> int:
        return self.dp * self.fsdp * self.tp


def _hbm_budget(devices: Optional[list]) -> float:
    """Usable HBM per chip: measured when the runtime exposes it, with the
    v5e constant as fallback (16 GB chip, ~12.5% headroom for XLA scratch)."""
    fallback = 14e9
    if not devices:
        return fallback
    try:
        stats = devices[0].memory_stats()
        limit = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
        if limit:
            return float(limit) * 0.875
    except Exception:  # backend without memory_stats (cpu, older plugins)
        pass
    return fallback


def plan_for(
    n_devices: int,
    *,
    tp: Optional[int] = None,
    fsdp: int = 1,
    config: Optional[ModelConfig] = None,
    devices: Optional[list] = None,
) -> MeshPlan:
    """Choose a mesh factorisation for ``n_devices``.

    Defaults: smallest tp that fits the model's KV heads evenly (tp must
    divide num_kv_heads so attention never crosses chips for one KV head),
    everything else data-parallel — the throughput-first layout for serving.
    """
    if tp is None:
        tp = 1
        if config is not None:
            # Llama-3-8B wants tp=4 on v5e-4 (16 GB HBM/chip); smaller models
            # run tp=1 and scale with dp alone
            approx_params = (
                config.vocab_size * config.hidden_size * 2
                + config.num_layers
                * (4 * config.hidden_size * config.num_heads * config.head_dim
                   + 3 * config.hidden_size * config.intermediate_size)
            )
            bytes_needed = approx_params * 2  # bf16
            hbm_per_chip = _hbm_budget(devices)
            while tp < n_devices and (bytes_needed / tp) > hbm_per_chip:
                tp *= 2
            while tp > 1 and config.num_kv_heads % tp != 0:
                tp //= 2
    if tp * fsdp > n_devices:
        raise ValueError(f"tp*fsdp={tp*fsdp} exceeds {n_devices} devices")
    dp = n_devices // (tp * fsdp)
    plan = MeshPlan(dp=dp, fsdp=fsdp, tp=tp)
    if plan.total != n_devices:
        log.warning("mesh uses %d of %d devices", plan.total, n_devices)
    return plan


def make_mesh(plan: Optional[MeshPlan] = None, devices: Optional[list] = None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    plan = plan or plan_for(len(devices), devices=devices)
    used = devices[: plan.total]
    array = np.asarray(used).reshape(plan.dp, plan.fsdp, plan.tp)
    return Mesh(array, AXES)


def initialize_distributed(**kwargs: Any) -> None:
    """Multi-host init over DCN.  Must run before anything touches the jax
    backend (so this function must not query devices/process_count itself —
    that would initialise a single-host backend and make later init fail).
    Initialises when the caller passes coordinator kwargs or the standard
    coordinator env vars are present; single-process launches no-op."""
    import os

    if kwargs or os.environ.get("JAX_COORDINATOR_ADDRESS") or os.environ.get(
        "COORDINATOR_ADDRESS"
    ):
        jax.distributed.initialize(**kwargs)


# --------------------------------------------------------------------------
# sharding rules
# --------------------------------------------------------------------------


def param_specs(
    config: ModelConfig, *, shard_fsdp: bool = True, quantized: bool = False
) -> Params:
    """PartitionSpecs mirroring the param pytree of ``llama.init_params``.

    Megatron-style TP: column-parallel in-projections (heads / MLP columns
    on ``tp``), row-parallel out-projections (XLA auto-inserts the psum on
    the residual add).  fsdp shards the *other* matrix axis so tp x fsdp
    tiles every large matrix fully.

    ``quantized`` mirrors the int8 tree (models/quant.py): each layer matrix
    becomes ``{q: <matrix spec>, s: <out-axis spec>}`` — per-output-channel
    scales shard exactly like the matrix's output axis.
    """
    f = "fsdp" if shard_fsdp else None
    layer_specs: dict[str, Any] = {
        "wq": P(None, f, "tp"),
        "wk": P(None, f, "tp"),
        "wv": P(None, f, "tp"),
        "wo": P(None, "tp", f),
        "w_gate": P(None, f, "tp"),
        "w_up": P(None, f, "tp"),
        "w_down": P(None, "tp", f),
        "ln_attn": P(None, None),
        "ln_mlp": P(None, None),
    }
    if config.attention_bias:
        # Qwen2 q/k/v biases live on the projections' OUTPUT axis, which is
        # tp-sharded — the bias add happens on the tp-local shard
        layer_specs["bq"] = P(None, "tp")
        layer_specs["bk"] = P(None, "tp")
        layer_specs["bv"] = P(None, "tp")
    if quantized:
        from ..models.quant import QUANTIZED_LAYER_MATRICES

        for name in QUANTIZED_LAYER_MATRICES:
            spec = layer_specs[name]
            layer_specs[name] = {"q": spec, "s": P(None, spec[2])}  # out axis
    specs: dict[str, Any] = {
        "embed": P(f, None),   # vocab-sharded over fsdp, hidden replicated
        "layers": layer_specs,
        "ln_final": P(None),
    }
    if not config.tie_embeddings:
        specs["lm_head"] = P(f, "tp")
    return specs


def param_shardings(mesh: Mesh, config: ModelConfig, **kw: Any) -> Params:
    return jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec), param_specs(config, **kw),
        is_leaf=lambda x: isinstance(x, P),
    )


def batch_spec() -> P:
    """Token/position batches shard over (dp, fsdp) jointly — fsdp acts as a
    second data axis at run time (ZeRO-style)."""
    return P(("dp", "fsdp"), None)


def kv_cache_spec() -> P:
    """[layers, B, S, kv_heads, head_dim]: batch over dp(+fsdp), heads over tp."""
    return P(None, ("dp", "fsdp"), None, "tp", None)


def paged_cache_specs() -> Any:
    """PartitionSpecs mirroring the ``PagedKVCache`` pytree.

    The page pool is shared by every sequence (any slot may hold any page),
    so the page axis can NOT shard over dp — pages shard over **tp on the
    KV-head axis** only, and dp parallelism comes from the batch-sharded
    queries/tokens.  The per-step token writes a dp shard contributes are
    [B/dp, 1, KH/tp, D] — kilobytes over ICI — so replicating the pool
    across dp costs bandwidth only at that scatter, not attention reads.
    Tables/lengths are tiny and replicated.
    """
    from ..ops.paged_attention import PagedKVCache

    pages = P(None, None, None, "tp", None)  # [L, pages, page_size, KH, D]
    return PagedKVCache(
        k_pages=pages, v_pages=pages, page_table=P(None, None), lengths=P(None)
    )


def logits_spec() -> P:
    return P(("dp", "fsdp"), None, "tp")


def shard_params(params: Params, mesh: Mesh, config: ModelConfig, **kw: Any) -> Params:
    """Place an existing (host or single-device) param tree onto the mesh."""
    shardings = param_shardings(mesh, config, **kw)
    return jax.tree_util.tree_map(jax.device_put, params, shardings)


def validate_param_shardings(
    mesh: Mesh, config: ModelConfig, *, quantized: bool = False
) -> int:
    """Prove every parameter leaf divides evenly over the mesh — WITHOUT
    allocating the model (``jax.eval_shape``).  Returns the leaf count.

    This is how the llama-3-8b factorisation (kv_heads=8 @ tp=4, vocab
    128256 over fsdp, quantized {q, s} trees) is checked on a virtual mesh
    before any real multi-chip run: ``NamedSharding.shard_shape`` raises on
    any axis a mesh dimension does not divide.
    """
    from ..models.llama import init_params

    def build(key):
        params = init_params(config, key)
        if quantized:
            from ..models.quant import quantize_params

            params = quantize_params(params, config)
        return params

    shapes = jax.eval_shape(build, jax.ShapeDtypeStruct((2,), np.uint32))
    shardings = param_shardings(mesh, config, quantized=quantized)
    leaves, treedef = jax.tree_util.tree_flatten(shapes)
    sharding_leaves = treedef.flatten_up_to(shardings)
    for leaf, sharding in zip(leaves, sharding_leaves):
        sharding.shard_shape(leaf.shape)  # raises on non-divisible axes
    return len(leaves)


def mesh_summary(mesh: Mesh) -> str:
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    return f"mesh {sizes} over {mesh.devices.size} {mesh.devices.flat[0].platform} device(s)"
