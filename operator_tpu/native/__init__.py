"""Native runtime components (C++ via ctypes) with pure-Python fallbacks.

The scanner (native/logscan.cpp) is compiled once per machine into
``OPERATOR_TPU_NATIVE_DIR`` (default: alongside this package) the first
time it's needed; any build/toolchain failure degrades silently to the
Python fallback so the framework never *requires* a compiler at runtime.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import tempfile
import threading
from typing import Optional, Sequence

log = logging.getLogger(__name__)

_SOURCE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
    "logscan.cpp",
)
_LIB_NAME = "liblogscan.so"
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_failed = False


def _lib_dir() -> str:
    configured = os.environ.get("OPERATOR_TPU_NATIVE_DIR")
    return configured or os.path.dirname(os.path.abspath(__file__))


def _build_library(target: str) -> Optional[str]:
    """Compile logscan.cpp to ``target`` (or a temp cache when the package
    dir is read-only); returns the built path or None."""
    if not os.path.exists(_SOURCE):
        return None
    if not os.access(os.path.dirname(target), os.W_OK):
        target = os.path.join(tempfile.gettempdir(), "operator_tpu_" + _LIB_NAME)
    try:
        with tempfile.TemporaryDirectory() as tmp:
            scratch = os.path.join(tmp, _LIB_NAME)
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SOURCE, "-o", scratch],
                check=True, capture_output=True, timeout=120,
            )
            os.replace(scratch, target)
        return target
    except (OSError, subprocess.SubprocessError) as exc:
        log.info("native scanner build skipped: %s", exc)
        return None


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_failed
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        target = os.path.join(_lib_dir(), _LIB_NAME)
        fallback = os.path.join(tempfile.gettempdir(), "operator_tpu_" + _LIB_NAME)
        path = next((p for p in (target, fallback) if os.path.exists(p)), None)
        if path is None:
            path = _build_library(target)
            if path is None:
                _lib_failed = True
                return None
        try:
            lib = ctypes.CDLL(path)
            lib.ls_build.restype = ctypes.c_void_p
            lib.ls_build.argtypes = [
                ctypes.POINTER(ctypes.c_char_p),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.c_int32,
            ]
            lib.ls_scan.restype = ctypes.c_int64
            lib.ls_scan.argtypes = [
                ctypes.c_void_p,
                ctypes.c_char_p,
                ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64,
            ]
            lib.ls_free.restype = None
            lib.ls_free.argtypes = [ctypes.c_void_p]
            _lib = lib
        except OSError as exc:
            log.warning("native scanner load failed (%s); using Python fallback", exc)
            _lib_failed = True
        return _lib


class _PyScanner:
    """Fallback: one ``bytes.find`` sweep per literal (C-speed inner loop,
    O(literals) passes instead of the automaton's single pass)."""

    def __init__(self, literals: Sequence[bytes]) -> None:
        self.literals = list(literals)

    def scan_arrays(self, text: bytes, max_hits: int = 1 << 20):
        import numpy as np

        ids: list[int] = []
        offsets: list[int] = []
        for literal_id, literal in enumerate(self.literals):
            if not literal:
                continue
            start = 0
            while len(ids) < max_hits:
                found = text.find(literal, start)
                if found < 0:
                    break
                ids.append(literal_id)
                offsets.append(found + len(literal) - 1)
                start = found + 1
        return np.asarray(ids, np.int32), np.asarray(offsets, np.int64)

    def scan(self, text: bytes, max_hits: int = 1 << 20) -> list[tuple[int, int]]:
        ids, offsets = self.scan_arrays(text, max_hits)
        return [(int(i), int(o)) for i, o in zip(ids, offsets)]


class _NativeScanner:
    def __init__(self, lib: ctypes.CDLL, literals: Sequence[bytes]) -> None:
        self._lib = lib
        array = (ctypes.c_char_p * len(literals))(*literals)
        lens = (ctypes.c_int32 * len(literals))(*[len(l) for l in literals])
        self._handle = lib.ls_build(array, lens, len(literals))

    def scan_arrays(self, text: bytes, max_hits: int = 1 << 20):
        import numpy as np

        out_ids = np.empty(max_hits, np.int32)
        out_offsets = np.empty(max_hits, np.int64)
        count = self._lib.ls_scan(
            self._handle,
            text,
            len(text),
            out_ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            out_offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            max_hits,
        )
        return out_ids[:count].copy(), out_offsets[:count].copy()

    def __del__(self):  # pragma: no cover - interpreter shutdown ordering
        try:
            if self._handle:
                self._lib.ls_free(self._handle)
                self._handle = None
        except (AttributeError, TypeError):
            pass


class MultiPatternScanner:
    """Find all occurrences of N byte literals in one text pass.

    ``scan`` returns (literal_id, end_offset) pairs; ``scan_arrays`` the
    same as two numpy arrays (the prefilter's vectorised path).  Backed by
    the C++ Aho-Corasick automaton when available, else the Python
    fallback.
    """

    def __init__(self, literals: Sequence[bytes]) -> None:
        lib = _load()
        self.native = lib is not None
        self._impl = (
            _NativeScanner(lib, literals) if lib is not None else _PyScanner(literals)
        )

    def scan_arrays(self, text: bytes, max_hits: Optional[int] = None):
        """-> (ids [N] int32, end_offsets [N] int64) numpy arrays.

        Never drops hits: a saturated buffer retries with 4x capacity
        (dropping would silently lose prefilter candidates = lost matches).
        """
        capacity = max_hits or max(4096, len(text) // 4)
        while True:
            ids, offsets = self._impl.scan_arrays(text, capacity)
            if len(ids) < capacity:
                return ids, offsets
            capacity *= 4

    def scan(self, text: bytes, max_hits: Optional[int] = None) -> list[tuple[int, int]]:
        ids, offsets = self.scan_arrays(text, max_hits)
        return [(int(i), int(o)) for i, o in zip(ids, offsets)]
