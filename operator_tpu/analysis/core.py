"""Framework core: findings, rules, pragma suppression, parsed sources.

A :class:`Rule` sees the whole project at once (:class:`AnalysisContext`)
because the interesting invariants are cross-file: jit entry points in
``serving/engine.py`` reach bodies defined in ``serving/programs.py`` and
``models/llama.py``, and the generated-artifact rule compares code against
``deploy/``.  Rules that only need one file at a time simply iterate
``ctx.modules``.

Suppression is explicit and auditable: a finding survives unless the
offending line (or its enclosing ``def``/``class`` line) carries

    # graftlint: disable=GL001 reason=why this is deliberate

The ``reason=`` clause is mandatory — a pragma without one does NOT
suppress (it surfaces as a GL000 malformed-pragma finding instead), so
every exception in the tree documents itself.
"""

from __future__ import annotations

import ast
import re
import threading
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterator, Optional

#: pragma grammar (GLxxx = rule id): ``graftlint: disable=GLxxx[,GLyyy] reason=<text to EOL>``
PRAGMA_RE = re.compile(
    r"#\s*graftlint:\s*disable=(?P<rules>[A-Z]{2}\d{3}(?:\s*,\s*[A-Z]{2}\d{3})*)"
    r"(?P<reason>\s+reason=\S.*)?"
)


@dataclass(frozen=True)
class Finding:
    """One rule violation at one place.

    ``symbol`` is the enclosing qualified name (``Class.method`` or a
    module-level function); with ``message`` it forms the baseline identity,
    so unrelated edits that shift line numbers do not churn the baseline.
    """

    rule: str
    path: str  # repo-relative, posix separators
    line: int
    message: str
    symbol: str = ""

    def key(self) -> tuple[str, str, str, str]:
        return (self.rule, self.path, self.symbol, self.message)

    def render(self) -> str:
        where = f"{self.path}:{self.line}"
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{where}: {self.rule}: {self.message}{sym}"


@dataclass
class Pragma:
    line: int
    rules: tuple[str, ...]
    has_reason: bool
    #: the pragma is the whole line (a standalone comment): it also covers
    #: the next source line, the own-line form used when the inline form
    #: would not fit
    standalone: bool = False


class ModuleSource:
    """One parsed Python file: source text, AST (with parent links), the
    pragma table, and the enclosing-scope index used for symbols and
    def-level suppression."""

    def __init__(self, root: Path, path: Path) -> None:
        self.abspath = path
        self.relpath = path.relative_to(root).as_posix()
        self.text = path.read_text(encoding="utf-8", errors="replace")
        self.lines = self.text.splitlines()
        self.tree: Optional[ast.Module] = None
        self.parse_error: Optional[str] = None
        try:
            self.tree = ast.parse(self.text)
        except SyntaxError as exc:  # surfaced as a finding by the runner
            self.parse_error = f"syntax error: {exc.msg} (line {exc.lineno})"
        if self.tree is not None:
            for node in ast.walk(self.tree):
                for child in ast.iter_child_nodes(node):
                    child._graftlint_parent = node  # type: ignore[attr-defined]
        self.pragmas = self._scan_pragmas()

    # -- pragmas -------------------------------------------------------
    def _scan_pragmas(self) -> dict[int, Pragma]:
        """Pragmas live in COMMENT tokens only — pragma-shaped text inside
        string literals and docstrings (rule documentation, test fixtures)
        must neither suppress nor trip the GL000 malformed-pragma check."""
        import io
        import tokenize

        pragmas: dict[int, Pragma] = {}
        try:
            tokens = tokenize.generate_tokens(io.StringIO(self.text).readline)
            for token in tokens:
                if token.type != tokenize.COMMENT:
                    continue
                match = PRAGMA_RE.search(token.string)
                if match is None:
                    continue
                lineno, col = token.start
                rules = tuple(
                    r.strip()
                    for r in match.group("rules").split(",")
                    if r.strip()
                )
                pragmas[lineno] = Pragma(
                    line=lineno,
                    rules=rules,
                    has_reason=match.group("reason") is not None,
                    standalone=token.line[:col].strip() == "",
                )
        except (tokenize.TokenError, IndentationError, SyntaxError):
            pass  # unparseable file: surfaced as a GL000 parse finding
        return pragmas

    def malformed_pragmas(self) -> list[Pragma]:
        return [p for p in self.pragmas.values() if not p.has_reason]

    def suppressed(self, rule: str, line: int) -> bool:
        """Is ``rule`` disabled at ``line``?  Honoured positions: the line
        itself, a standalone pragma comment on the line above, or the
        ``def``/``class`` header of any enclosing scope."""
        pragma = self.pragmas.get(line)
        if pragma and pragma.has_reason and rule in pragma.rules:
            return True
        above = self.pragmas.get(line - 1)
        if (
            above is not None
            and above.standalone
            and above.has_reason
            and rule in above.rules
        ):
            return True
        for scope in self._enclosing_scopes(line):
            pragma = self.pragmas.get(scope.lineno)
            if pragma and pragma.has_reason and rule in pragma.rules:
                return True
        return False

    # -- scopes --------------------------------------------------------
    _SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)

    def _enclosing_scopes(self, line: int) -> Iterator[ast.AST]:
        if self.tree is None:
            return
        for node in ast.walk(self.tree):
            if isinstance(node, self._SCOPE_NODES):
                end = getattr(node, "end_lineno", node.lineno)
                if node.lineno <= line <= (end or node.lineno):
                    yield node

    def symbol_at(self, node: ast.AST) -> str:
        """``Class.method`` / ``func`` / ``func.<locals>.inner`` for the
        scope enclosing ``node`` (the node itself when it is a def)."""
        chain: list[str] = []
        current: Optional[ast.AST] = node
        while current is not None:
            if isinstance(current, self._SCOPE_NODES):
                chain.append(current.name)
            current = getattr(current, "_graftlint_parent", None)
        return ".".join(reversed(chain))


@dataclass
class AnalysisContext:
    """Everything a rule may look at: the repo root and every parsed module
    under the analysed trees.  ``module(relpath)`` is the per-file lookup;
    rules with generated-artifact checks also read non-Python files through
    ``root``.

    The context is also the per-run memo: parsed ASTs live in ``modules``
    (one parse per file per run, however many rules look at it), and
    :meth:`memo` / :meth:`symbol_tables` share expensive derived structures
    — symbol/callgraph tables, the jit reachability graph, auxiliary
    out-of-scope parses — across rules.  Both are thread-safe so rules can
    run concurrently under ``--jobs``."""

    root: Path
    modules: list[ModuleSource] = field(default_factory=list)
    _by_path: dict[str, ModuleSource] = field(default_factory=dict)
    #: scratch space for cross-rule shared computations (e.g. the jit
    #: reachability graph GL001 and GL002 both need, the callgraph tables
    #: GL006/GL011/GL012 share) — access through :meth:`memo`
    caches: dict = field(default_factory=dict)
    #: reentrant: a memoized builder may itself read other memo entries
    #: (GL012's package enumeration parses aux modules)
    _memo_lock: "threading.RLock" = field(default_factory=threading.RLock)

    def add(self, module: ModuleSource) -> None:
        self.modules.append(module)
        self._by_path[module.relpath] = module

    def module(self, relpath: str) -> Optional[ModuleSource]:
        return self._by_path.get(relpath)

    def in_scope(self, patterns: tuple[str, ...]) -> list[ModuleSource]:
        out = []
        for module in self.modules:
            if any(re.match(pattern, module.relpath) for pattern in patterns):
                out.append(module)
        return out

    def memo(self, key, builder):
        """``caches[key]``, built once under the lock.  Rules running in
        parallel (``--jobs``) must reach every shared computation through
        here — two threads racing the same build would each pay the cost
        and the loser's result would be silently dropped."""
        with self._memo_lock:
            value = self.caches.get(key)
            if value is None:
                value = builder()
                self.caches[key] = value
        return value

    def symbol_tables(self, modules: list["ModuleSource"]):
        """Shared :class:`~.callgraph.SymbolTables` over ``modules``,
        memoized by the module set — rules with the same scope (GL011 and
        GL012 both walk the control plane) build the tables once per run
        instead of once per rule."""
        from .callgraph import SymbolTables

        key = ("symbol_tables", tuple(sorted(m.relpath for m in modules)))
        return self.memo(key, lambda: SymbolTables(modules))

    def aux_module(self, relpath: str) -> Optional["ModuleSource"]:
        """Parse a repo file OUTSIDE the collected set (e.g. ``tests/``
        under ``--changed-only``), memoized.  Returns the in-context module when
        the path was collected normally.  None when the file is missing."""
        hit = self._by_path.get(relpath)
        if hit is not None:
            return hit

        def build():
            path = self.root / relpath
            if not path.is_file():
                return ()
            return ModuleSource(self.root, path)

        built = self.memo(("aux_module", relpath), build)
        return None if built == () else built


class Rule:
    """Base class: subclasses set ``id``/``name``/``description`` and
    implement :meth:`check`.  ``scope`` documents (and restricts) which
    repo-relative paths the rule inspects — regex, anchored at start."""

    id: str = "GL000"
    name: str = "abstract"
    description: str = ""
    scope: tuple[str, ...] = (r".*\.py$",)

    def check(self, ctx: AnalysisContext) -> list[Finding]:
        raise NotImplementedError

    # helper shared by every AST rule
    def finding(
        self, module: ModuleSource, node: ast.AST, message: str
    ) -> Finding:
        return Finding(
            rule=self.id,
            path=module.relpath,
            line=getattr(node, "lineno", 1),
            message=message,
            symbol=module.symbol_at(node),
        )
