"""Committed baseline of grandfathered findings.

The baseline keys findings by (rule, path, symbol, message) with an
occurrence count — never by line number, so unrelated edits that shift code
do not churn it.  The workflow (docs/ANALYSIS.md):

- ``--write-baseline`` records the current findings;
- a normal run fails only on findings NOT in the baseline;
- baseline entries that no longer match anything are reported as *stale*
  (the debt was paid — remove the entry) but do not fail the gate.

Policy note: the baseline exists for migrations, not as a dumping ground —
deliberate, permanent exceptions belong in the source as
``# graftlint: disable=GLxxx reason=...`` pragmas where reviewers see them.
"""

from __future__ import annotations

import json
from collections import Counter
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable

from .core import Finding

_KEY_FIELDS = ("rule", "path", "symbol", "message")


@dataclass
class Baseline:
    """Multiset of grandfathered finding keys."""

    counts: Counter = field(default_factory=Counter)

    @classmethod
    def from_findings(cls, findings: Iterable[Finding]) -> "Baseline":
        return cls(Counter(f.key() for f in findings))

    def filter(
        self, findings: list[Finding]
    ) -> tuple[list[Finding], list[tuple[str, str, str, str]]]:
        """Split ``findings`` into (new, stale-baseline-keys).  Each baseline
        entry absorbs at most its recorded count of matching findings."""
        budget = Counter(self.counts)
        new: list[Finding] = []
        for finding in findings:
            key = finding.key()
            if budget[key] > 0:
                budget[key] -= 1
            else:
                new.append(finding)
        stale = sorted(key for key, left in budget.items() if left > 0)
        return new, stale


def load_baseline(path: Path) -> Baseline:
    data = json.loads(path.read_text(encoding="utf-8"))
    counts: Counter = Counter()
    for entry in data.get("findings", []):
        key = tuple(str(entry.get(k, "")) for k in _KEY_FIELDS)
        counts[key] += int(entry.get("count", 1))
    return Baseline(counts)


def write_baseline(path: Path, findings: Iterable[Finding]) -> None:
    baseline = Baseline.from_findings(findings)
    entries = [
        {
            "rule": rule,
            "path": rel,
            "symbol": symbol,
            "message": message,
            "count": count,
        }
        for (rule, rel, symbol, message), count in sorted(baseline.counts.items())
    ]
    payload = {
        "comment": (
            "graftlint baseline — grandfathered findings only; new code must "
            "be clean and deliberate exceptions use inline pragmas "
            "(docs/ANALYSIS.md)"
        ),
        "findings": entries,
    }
    path.write_text(json.dumps(payload, indent=2) + "\n", encoding="utf-8")
