"""Jit/Pallas reachability + taint — the shared engine behind GL001/GL002.

Purely syntactic (``ast``), no jax import.  Three passes:

1. **Entry detection** — every function that becomes a compiled program:
   ``@jax.jit`` / ``@partial(jax.jit, static_argnames=...)`` decorated defs,
   functions passed to ``jax.jit(...)`` / ``jit(...)`` by name
   (``jax.jit(self._decode_block, donate_argnums=(1,))``), lambdas inside a
   jit call, and kernels handed to ``pl.pallas_call`` (directly or through
   ``functools.partial``).
2. **Reachability** — from each entry, resolve calls through module-level
   functions, ``from x import y`` imports within the analysed set, and
   ``self.method`` lookups across every analysed class (the generator is
   assembled from mixins, so method resolution is deliberately
   class-agnostic).  Higher-order wrappers (``lax.scan``, ``vmap``,
   ``partial``, ``checkpoint``/``remat``) treat function-valued arguments
   as calls; nested ``def``s of a reachable function are reachable.
3. **Taint** — which names hold traced values: entry parameters minus
   ``static_argnames`` minus ``self``, anything produced by a ``jnp.*`` /
   ``jax.*`` call, and everything derived from those.  Shape/dtype metadata
   (``x.shape``, ``x.ndim``, ``x.dtype``, ``x.size``, ``len(x)``) is static
   at trace time and sanitises; so do ``is``/``is not`` comparisons
   (pytree-None dispatch is resolved at trace time).  Taint propagates into
   callees per call site (positional + keyword mapping) to a fixpoint.

Heuristic boundaries, documented for rule consumers: attributes of ``self``
are treated as host configuration (untainted) — per-slot device state hung
on the generator is read through parameters in this codebase — and free
variables of nested functions default to untainted.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Optional

from .callgraph import (
    DEF_NODES as _DEF_NODES,
    SymbolTables,
    attr_chain as _attr_chain,
    func_root as _func_root,
    iter_scope,
)
from .core import AnalysisContext, ModuleSource
#: attribute accesses that yield static (host) metadata at trace time
SANITIZING_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "sharding"}
#: builtins whose result is a static host value
SANITIZING_CALLS = {"len", "isinstance", "range", "type", "hasattr", "getattr"}
#: roots whose calls produce traced arrays even from static args
ARRAY_NAMESPACES = {"jnp", "lax", "pl", "pltpu"}
#: ``jax.<second>.*`` namespaces that produce arrays (``jax.devices()`` /
#: ``jax.default_backend()`` style introspection stays host-static)
JAX_ARRAY_SUBMODULES = {"lax", "nn", "numpy", "random", "scipy"}
#: higher-order wrappers whose function-valued args are effectively called
HOF_NAMES = {"scan", "vmap", "pmap", "checkpoint", "remat", "partial",
             "fori_loop", "while_loop", "cond", "switch", "custom_vjp",
             "shard_map", "named_call"}
#: trace-inert context managers: profiler/span metadata that neither
#: syncs the host nor yields traced values — ``jax.profiler
#: .TraceAnnotation``/``StepTraceAnnotation``, ``jax.named_scope``, and
#: the obs tracer's ``span()``/``trace()`` (operator_tpu/obs/span.py).
#: The serving engine wraps its prefill/decode dispatches in these
#: (engine._annotation); GL001/GL002 must stay quiet on them, and taint
#: must not flow out of them (their return is a context object, not an
#: array).
TRACE_INERT_CALLS = {"TraceAnnotation", "StepTraceAnnotation",
                     "named_scope", "_annotation"}
#: receivers whose ``.span()``/``.trace()`` methods are span context
#: managers, not array ops — ``jnp.trace(x)`` (the matrix trace!) must
#: stay tainted, so the generic method names require a tracer-shaped
#: receiver
_TRACER_RECEIVERS = {"profiler", "tracer", "obs", "TRACER"}


def is_trace_inert_call(func: ast.AST) -> bool:
    """Is this call a trace/profiler annotation (see TRACE_INERT_CALLS)?"""
    chain = _attr_chain(func)
    if not chain:
        return False
    if chain[-1] in TRACE_INERT_CALLS:
        return True
    if chain[-1] in ("span", "trace"):
        if chain == ["span"] or chain == ["obs_span"]:
            return True  # `from operator_tpu.obs import span [as obs_span]`
        if len(chain) >= 2 and (
            chain[-2] in _TRACER_RECEIVERS or "trace" in chain[-2].lower()
        ):
            return True  # jax.profiler.trace / self.tracer.span / obs.span
    return False


def _is_array_namespace_call(func: ast.AST) -> bool:
    chain = _attr_chain(func)
    if not chain:
        return False
    if chain[0] in ARRAY_NAMESPACES:
        return True
    return chain[0] == "jax" and len(chain) > 2 and chain[1] in JAX_ARRAY_SUBMODULES


def _is_jit_ref(func: ast.AST) -> bool:
    if isinstance(func, ast.Name):
        return func.id == "jit"
    return isinstance(func, ast.Attribute) and func.attr == "jit"


def _is_pallas_ref(func: ast.AST) -> bool:
    if isinstance(func, ast.Name):
        return func.id == "pallas_call"
    return isinstance(func, ast.Attribute) and func.attr == "pallas_call"


def _static_argnames(call: ast.Call) -> set[str]:
    names: set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for node in ast.walk(kw.value):
                if isinstance(node, ast.Constant) and isinstance(node.value, str):
                    names.add(node.value)
    return names


@dataclass
class FunctionInfo:
    """One def (or lambda) in the analysed set."""

    node: ast.AST  # FunctionDef | AsyncFunctionDef | Lambda
    module: ModuleSource
    qualname: str
    is_entry: bool = False
    entry_kind: str = ""  # "jit" | "pallas"
    static_params: set[str] = field(default_factory=set)
    tainted_params: set[str] = field(default_factory=set)
    reachable: bool = False

    @property
    def params(self) -> list[str]:
        args = self.node.args
        names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
        if args.vararg:
            names.append(args.vararg.arg)
        if args.kwarg:
            names.append(args.kwarg.arg)
        return names


class JitGraph:
    """Reachability/taint index over a set of modules (see module doc)."""

    @classmethod
    def for_modules(
        cls, ctx: AnalysisContext, modules: list[ModuleSource]
    ) -> "JitGraph":
        """Cached constructor: GL001 and GL002 share one scope, so the
        fixpoint (the expensive half of the analysis) runs once per run."""
        key = ("jitgraph", tuple(m.relpath for m in modules))
        graph = ctx.caches.get(key)
        if graph is None:
            graph = cls(ctx, modules)
            ctx.caches[key] = graph
        return graph

    def __init__(self, ctx: AnalysisContext, modules: list[ModuleSource]) -> None:
        self.ctx = ctx
        self.modules = [m for m in modules if m.tree is not None]
        self._infos: dict[int, FunctionInfo] = {}  # id(node) -> info
        #: shared syntactic tables (callgraph.py) — the same resolution
        #: semantics GL006's async walk uses
        self._tables = SymbolTables(self.modules)
        self._build_infos()
        self._detect_entries()
        self._propagate()

    # -- construction --------------------------------------------------
    def _build_infos(self) -> None:
        for module in self.modules:
            for node in ast.walk(module.tree):
                if isinstance(node, _DEF_NODES):
                    self._infos[id(node)] = FunctionInfo(
                        node=node, module=module,
                        qualname=module.symbol_at(node),
                    )

    def info(self, node: ast.AST) -> Optional[FunctionInfo]:
        return self._infos.get(id(node))

    def reachable_functions(self) -> list[FunctionInfo]:
        return [i for i in self._infos.values() if i.reachable]

    # -- entry detection -----------------------------------------------
    def _detect_entries(self) -> None:
        for module in self.modules:
            for node in ast.walk(module.tree):
                if isinstance(node, _DEF_NODES):
                    self._check_decorators(module, node)
                elif isinstance(node, ast.Call):
                    self._check_call(module, node)

    def _check_decorators(self, module: ModuleSource, node: ast.AST) -> None:
        for deco in node.decorator_list:
            if _is_jit_ref(deco):
                self._mark_entry(self.info(node), "jit", set())
            elif isinstance(deco, ast.Call):
                if _is_jit_ref(deco.func):
                    self._mark_entry(self.info(node), "jit", _static_argnames(deco))
                elif deco.args and _is_jit_ref(deco.args[0]):
                    # @partial(jax.jit, static_argnames=...)
                    self._mark_entry(self.info(node), "jit", _static_argnames(deco))

    def _check_call(self, module: ModuleSource, call: ast.Call) -> None:
        if _is_jit_ref(call.func) and call.args:
            target = call.args[0]
            statics = _static_argnames(call)
            for info in self._resolve_function_ref(module, call, target):
                self._mark_entry(info, "jit", statics)
        elif _is_pallas_ref(call.func) and call.args:
            target = call.args[0]
            if isinstance(target, ast.Call):  # partial(kernel, ...)
                target = target.args[0] if target.args else target
            for info in self._resolve_function_ref(module, call, target):
                self._mark_entry(info, "pallas", set())

    def _mark_entry(
        self, info: Optional[FunctionInfo], kind: str, statics: set[str]
    ) -> None:
        if info is None:
            return
        info.is_entry = True
        info.entry_kind = info.entry_kind or kind
        info.static_params.update(statics)
        traced = {
            p for p in info.params
            if p not in info.static_params and p != "self"
        }
        info.tainted_params.update(traced)

    # -- name resolution -----------------------------------------------
    def _resolve_function_ref(
        self, module: ModuleSource, site: ast.AST, target: ast.AST
    ) -> list[FunctionInfo]:
        """Defs a function-valued expression can denote."""
        if isinstance(target, ast.Lambda):
            info = self._infos.get(id(target))
            if info is None:
                info = FunctionInfo(
                    node=target, module=module,
                    qualname=f"{module.symbol_at(target)}.<lambda>",
                )
                self._infos[id(target)] = info
            return [info]
        nodes = self._tables.resolve_ref(module, site, target)
        return [
            self._infos[id(node)] for node in nodes if id(node) in self._infos
        ]

    # -- reachability + taint fixpoint ---------------------------------
    def _propagate(self) -> None:
        self._resolve_returns = False
        self._return_memo: dict[int, bool] = {}
        worklist = [i for i in self._infos.values() if i.is_entry]
        for info in worklist:
            info.reachable = True
        while worklist:
            info = worklist.pop()
            env = self.local_taint(info)
            body = (
                info.node.body
                if isinstance(info.node.body, list)
                else [ast.Expr(info.node.body)]  # lambda
            )
            for stmt in body:
                for node in iter_scope(stmt):
                    if isinstance(node, _DEF_NODES):
                        # a DECORATED nested def (@pl.when(...)) is invoked
                        # by traced machinery with traced values; plain
                        # nested defs become reachable through their call
                        # sites (precise per-site taint mapping)
                        if not node.decorator_list:
                            continue
                        nested = self._infos[id(node)]
                        if not nested.reachable:
                            nested.reachable = True
                            nested.tainted_params.update(
                                p for p in nested.params if p != "self"
                            )
                            worklist.append(nested)
                        continue
                    if not isinstance(node, ast.Call):
                        continue
                    for callee, args_taint in self._resolve_call(
                        info.module, node, env
                    ):
                        changed = not callee.reachable
                        callee.reachable = True
                        before = len(callee.tainted_params)
                        callee.tainted_params.update(args_taint)
                        if changed or len(callee.tainted_params) != before:
                            worklist.append(callee)
        # from here on expr_tainted may resolve call return taint through
        # the (now stable) per-function taint sets
        self._resolve_returns = True

    def _resolve_call(
        self, module: ModuleSource, call: ast.Call, env: set[str]
    ) -> list[tuple[FunctionInfo, set[str]]]:
        """(callee, tainted-param-names) pairs for one call site."""
        out: list[tuple[FunctionInfo, set[str]]] = []
        targets: list[ast.AST] = []
        func = call.func
        if isinstance(func, ast.Name) or (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id == "self"
        ):
            targets.append(func)
        # higher-order wrappers: function-valued args are called with
        # traced values (scan carries, vmapped batches)
        attr = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else ""
        )
        if attr in HOF_NAMES:
            for arg in call.args:
                for info in self._resolve_function_ref(module, call, arg):
                    out.append(
                        (info, {p for p in info.params if p != "self"})
                    )
        for target in targets:
            for info in self._resolve_function_ref(module, call, target):
                out.append((info, self._map_taint(info, call, env)))
        return out

    def _map_taint(
        self, callee: FunctionInfo, call: ast.Call, env: set[str]
    ) -> set[str]:
        params = [p for p in callee.params if p != "self"]
        tainted: set[str] = set()
        for idx, arg in enumerate(call.args):
            if isinstance(arg, ast.Starred):
                # can't map positions past a splat: taint the rest
                tainted.update(params[idx:])
                break
            if idx < len(params) and self.expr_tainted(arg, env):
                tainted.add(params[idx])
        for kw in call.keywords:
            if kw.arg is not None and self.expr_tainted(kw.value, env):
                tainted.add(kw.arg)
        return tainted & set(params)

    # -- taint ----------------------------------------------------------
    def local_taint(self, info: FunctionInfo) -> set[str]:
        """Names holding traced values inside ``info``: tainted params plus
        assignment targets of tainted expressions (iterated to fixpoint —
        straight-line reassignment chains converge in a few passes)."""
        env = set(info.tainted_params)
        body = (
            info.node.body if isinstance(info.node.body, list) else []
        )
        for _ in range(8):
            before = len(env)
            for stmt in body:
                for node in iter_scope(stmt):
                    if isinstance(node, _DEF_NODES):
                        continue
                    targets: list[ast.AST] = []
                    value: Optional[ast.AST] = None
                    if isinstance(node, ast.Assign):
                        targets, value = node.targets, node.value
                    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                        targets, value = [node.target], node.value
                    elif isinstance(node, ast.For):
                        targets, value = [node.target], node.iter
                    if value is not None and self.expr_tainted(
                        value, env, module=info.module
                    ):
                        for target in targets:
                            for leaf in ast.walk(target):
                                if isinstance(leaf, ast.Name):
                                    env.add(leaf.id)
            if len(env) == before:
                break
        return env

    def _return_tainted(self, info: FunctionInfo) -> bool:
        """Does a call to ``info`` yield a traced value?  Computed from its
        (post-fixpoint) tainted params and return expressions; cycles
        resolve conservatively to tainted."""
        memo = self._return_memo
        key = id(info.node)
        if key in memo:
            return memo[key]
        memo[key] = True  # in-progress: recursion assumes tainted
        if not isinstance(info.node.body, list):  # lambda
            result = self.expr_tainted(
                info.node.body, set(info.tainted_params), module=info.module
            )
        else:
            env = self.local_taint(info)
            result = False
            for stmt in info.node.body:
                for node in iter_scope(stmt):
                    if isinstance(node, _DEF_NODES):
                        continue
                    if isinstance(node, ast.Return) and node.value is not None:
                        if self.expr_tainted(node.value, env, module=info.module):
                            result = True
                            break
                if result:
                    break
        memo[key] = result
        return result

    def expr_tainted(
        self,
        expr: ast.AST,
        env: set[str],
        module: Optional[ModuleSource] = None,
    ) -> bool:
        if isinstance(expr, ast.Name):
            return expr.id in env
        if isinstance(expr, ast.Constant):
            return False
        if isinstance(expr, ast.Attribute):
            if expr.attr in SANITIZING_ATTRS:
                return False
            if isinstance(expr.value, ast.Name) and expr.value.id == "self":
                return False  # host-owned configuration (module doc)
            return self.expr_tainted(expr.value, env, module)
        if isinstance(expr, ast.Compare):
            if all(
                isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                for op in expr.ops
            ):
                # static pytree-None dispatch / dict-membership config
                # checks (`name in layer_lora`); membership on an actual
                # traced ARRAY would be a real bug but the jit trace
                # itself rejects it loudly
                return False
            return any(
                self.expr_tainted(e, env, module)
                for e in [expr.left, *expr.comparators]
            )
        if isinstance(expr, ast.Call):
            func = expr.func
            if isinstance(func, ast.Name) and func.id in SANITIZING_CALLS:
                return False
            # BEFORE the array-namespace check: jax.profiler.* and
            # jax.named_scope are jax-rooted but trace-inert — their
            # result is a context object, never a traced array
            if is_trace_inert_call(func):
                return False
            if _is_array_namespace_call(func):
                return True
            # resolved local/imported/self calls: taint of their returns
            if self._resolve_returns and module is not None:
                infos = self._resolve_function_ref(module, expr, func)
                if infos:
                    return any(self._return_tainted(i) for i in infos)
            if isinstance(func, ast.Attribute) and self.expr_tainted(
                func.value, env, module
            ):
                return True  # method on a traced value
            return any(
                self.expr_tainted(a, env, module) for a in expr.args
            ) or any(
                self.expr_tainted(kw.value, env, module)
                for kw in expr.keywords
            )
        if isinstance(expr, ast.BoolOp):
            return any(self.expr_tainted(v, env, module) for v in expr.values)
        if isinstance(expr, ast.BinOp):
            return self.expr_tainted(
                expr.left, env, module
            ) or self.expr_tainted(expr.right, env, module)
        if isinstance(expr, ast.UnaryOp):
            return self.expr_tainted(expr.operand, env, module)
        if isinstance(expr, ast.Subscript):
            return self.expr_tainted(
                expr.value, env, module
            ) or self.expr_tainted(expr.slice, env, module)
        if isinstance(expr, ast.IfExp):
            return any(
                self.expr_tainted(e, env, module)
                for e in [expr.test, expr.body, expr.orelse]
            )
        if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
            return any(self.expr_tainted(e, env, module) for e in expr.elts)
        if isinstance(expr, ast.Starred):
            return self.expr_tainted(expr.value, env, module)
        return False
