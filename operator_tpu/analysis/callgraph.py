"""Shared syntactic call-graph machinery for graftlint rules.

Extracted from ``jitgraph.py`` (which grew it for GL001/GL002's
jit/Pallas reachability) so GL006's async-reachability walk rides the
SAME resolution semantics instead of a second drifting copy:

- :func:`iter_scope` — statement walk that does NOT descend into nested
  function/lambda subtrees (each def is its own scope);
- :func:`attr_chain` / :func:`func_root` — dotted-call-target helpers;
- :class:`SymbolTables` — per-module function tables, ``from x import
  y`` resolution within the analysed set, class-agnostic method lookup,
  and :meth:`SymbolTables.resolve_ref`: the defs a function-valued
  expression can denote.

Resolution is deliberately class-agnostic for method references (the
serving generator is assembled from mixins; the operator wires
collaborators by attribute) — a ``<recv>.method`` reference resolves to
every analysed method of that name.  Callers that cannot afford the
imprecision on non-``self`` receivers restrict it via
``method_names_ok`` (GL006 drops generic container-protocol names like
``append``/``get`` there, where ``self``-dispatch keeps them).
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from .core import ModuleSource

DEF_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)

__all__ = [
    "DEF_NODES",
    "iter_scope",
    "func_root",
    "attr_chain",
    "SymbolTables",
]


def iter_scope(stmt: ast.AST):
    """Walk a statement WITHOUT descending into nested function/lambda
    subtrees.  Nested defs are yielded (so callers can register them) but
    their bodies belong to their own scope: a nested helper's locals,
    returns and calls must never leak into the enclosing function's
    analysis (each reachable nested def is analysed as its own unit)."""
    stack: list[ast.AST] = [stmt]
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (*DEF_NODES, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def func_root(func: ast.AST) -> Optional[str]:
    """Leftmost name of a (possibly dotted) call target."""
    while isinstance(func, ast.Attribute):
        func = func.value
    return func.id if isinstance(func, ast.Name) else None


def attr_chain(func: ast.AST) -> list[str]:
    """``jax.lax.scan`` -> ["jax", "lax", "scan"]; [] when not a pure
    name/attribute chain."""
    parts: list[str] = []
    while isinstance(func, ast.Attribute):
        parts.append(func.attr)
        func = func.value
    if isinstance(func, ast.Name):
        parts.append(func.id)
        return list(reversed(parts))
    return []


class SymbolTables:
    """Function/method/import tables over a set of parsed modules.

    One instance per (rule, scope) — building is a single AST walk per
    module; resolution is dict lookups plus a lexical-scope climb."""

    def __init__(self, modules: Iterable[ModuleSource]) -> None:
        self.modules = [m for m in modules if m.tree is not None]
        self.relpaths = {m.relpath for m in self.modules}
        #: relpath -> {module-level function name -> def node}
        self.module_funcs: dict[str, dict[str, ast.AST]] = {}
        #: method name -> every class-body def node of that name
        self.methods_by_name: dict[str, list[ast.AST]] = {}
        #: relpath -> {local name -> (target relpath, original name)}
        self.imports: dict[str, dict[str, tuple[str, str]]] = {}
        #: def node id -> owning module (resolution output needs it)
        self.module_of: dict[int, ModuleSource] = {}
        for module in self.modules:
            funcs: dict[str, ast.AST] = {}
            for node in ast.walk(module.tree):
                if isinstance(node, DEF_NODES):
                    self.module_of[id(node)] = module
                    parent = getattr(node, "_graftlint_parent", None)
                    if isinstance(parent, ast.Module):
                        funcs[node.name] = node
                    elif isinstance(parent, ast.ClassDef):
                        self.methods_by_name.setdefault(
                            node.name, []
                        ).append(node)
            self.module_funcs[module.relpath] = funcs
            self.imports[module.relpath] = self._scan_imports(module)

    def _scan_imports(
        self, module: ModuleSource
    ) -> dict[str, tuple[str, str]]:
        """local name -> (target module relpath, original name) for
        ``from X import y [as z]`` imports resolvable inside the set."""
        out: dict[str, tuple[str, str]] = {}
        package_parts = module.relpath.split("/")[:-1]
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.ImportFrom):
                continue
            if node.level:
                base = package_parts[: len(package_parts) - (node.level - 1)]
            else:
                base = []
            target = base + (node.module.split(".") if node.module else [])
            rel = "/".join(target) + ".py"
            if rel not in self.relpaths:
                continue
            for alias in node.names:
                out[alias.asname or alias.name] = (rel, alias.name)
        return out

    def resolve_ref(
        self,
        module: ModuleSource,
        site: ast.AST,
        target: ast.AST,
        *,
        non_self_methods: bool = False,
        method_names_ok=None,
    ) -> list[ast.AST]:
        """Def nodes a function-valued expression can denote.

        ``self.method`` always resolves class-agnostically.  With
        ``non_self_methods=True``, ``<any receiver>.method`` does too —
        gated by ``method_names_ok`` (a predicate on the method name)
        because generic protocol names (``get``, ``append``) would
        otherwise alias half the analysed tree."""
        if isinstance(target, ast.Attribute):
            is_self = (
                isinstance(target.value, ast.Name)
                and target.value.id == "self"
            )
            if is_self or non_self_methods:
                candidates = self.methods_by_name.get(target.attr, [])
                if not is_self and method_names_ok is not None:
                    if not method_names_ok(target.attr):
                        return []
                return list(candidates)
            return []
        if not isinstance(target, ast.Name):
            return []
        name = target.id
        # nearest lexically-enclosing def holding a nested def of that name
        scope = getattr(site, "_graftlint_parent", None)
        while scope is not None:
            if isinstance(scope, DEF_NODES):
                for child in ast.walk(scope):
                    if (
                        isinstance(child, DEF_NODES)
                        and child.name == name
                        and child is not scope
                    ):
                        return [child]
            scope = getattr(scope, "_graftlint_parent", None)
        local = self.module_funcs.get(module.relpath, {}).get(name)
        if local is not None:
            return [local]
        imported = self.imports.get(module.relpath, {}).get(name)
        if imported is not None:
            rel, orig = imported
            other = self.module_funcs.get(rel, {}).get(orig)
            if other is not None:
                return [other]
        return []
