"""Project walker + rule executor: collect sources, run rules, suppress.

Separated from ``__main__`` so tests (and future in-process consumers, e.g.
a pre-commit hook) can run the analysis without a subprocess.
"""

from __future__ import annotations

import subprocess
import time
from pathlib import Path
from typing import Optional, Sequence

from .core import AnalysisContext, Finding, ModuleSource, Rule

#: directories never worth parsing
_SKIP_DIRS = {
    "__pycache__", ".git", ".pytest_cache", ".hypothesis", "build",
    "node_modules", ".venv", "venv", "env", ".tox", ".eggs",
    ".mypy_cache", "site-packages",
}


def collect_context(root: Path, paths: Optional[Sequence[Path]] = None) -> AnalysisContext:
    """Parse every ``.py`` under ``paths`` (default: the whole tree) into an
    :class:`AnalysisContext` rooted at ``root``."""
    root = root.resolve()
    ctx = AnalysisContext(root=root)
    roots = [Path(p).resolve() for p in paths] if paths else [root]
    seen: set[Path] = set()
    for base in roots:
        if not base.exists():
            # a typo'd path in a CI command must fail loudly, never turn
            # the gate into "clean — 0 file(s)"
            raise FileNotFoundError(f"no such path: {base}")
        if not base.is_relative_to(root):
            raise ValueError(
                f"{base} is outside the analysis root {root} — finding "
                "paths are root-relative; pass --root accordingly"
            )
        candidates = [base] if base.is_file() else sorted(base.rglob("*.py"))
        for path in candidates:
            if path.suffix != ".py" or path in seen:
                continue
            if any(part in _SKIP_DIRS for part in path.parts):
                continue
            seen.add(path)
            ctx.add(ModuleSource(root, path))
    return ctx


def changed_paths(root: Path, ref: str) -> list[Path]:
    """Python files under ``root`` that differ from git ``ref`` (committed
    diff + untracked), for ``--changed-only`` pre-commit runs.  Deleted
    files are dropped (nothing to parse); a bad ref raises ValueError so
    the CLI can fail loudly instead of reporting a clean empty run."""
    root = root.resolve()

    def _git(*argv: str) -> list[str]:
        proc = subprocess.run(
            ["git", "-C", str(root), *argv],
            capture_output=True, text=True, timeout=30,
        )
        if proc.returncode != 0:
            raise ValueError(
                f"git {' '.join(argv)} failed: {proc.stderr.strip()}"
            )
        return [line for line in proc.stdout.splitlines() if line.strip()]

    names = set(_git("diff", "--name-only", ref, "--"))
    names.update(_git("ls-files", "--others", "--exclude-standard"))
    out = []
    for name in sorted(names):
        path = root / name
        if path.suffix == ".py" and path.exists():
            out.append(path)
    return out


def run_analysis(
    ctx: AnalysisContext,
    rules: Sequence[Rule],
    timings: Optional[dict] = None,
    jobs: int = 1,
) -> tuple[list[Finding], list[Finding]]:
    """Run ``rules`` over ``ctx``.

    Returns ``(findings, pragma_errors)``: rule findings surviving pragma
    suppression (sorted by location), plus one GL000 finding per malformed
    pragma (``disable=`` without ``reason=`` — a suppression that does not
    document itself does not suppress).  When ``timings`` is a dict it is
    filled with per-rule wall seconds (rule id -> float) — the lint job
    prints these so a rule that grows quadratic pain is caught in review,
    not discovered as a slow CI mystery later.

    ``jobs > 1`` runs rules concurrently on a thread pool.  Rules are
    independent by contract — everything shared (parsed ASTs, symbol
    tables, the jit graph) is read through the context's thread-safe memo
    — and results are merged back in catalogue order, so the output is
    byte-identical to a serial run.  Per-rule ``timings`` remain wall
    times of each rule's own check, not of the pool.
    """

    def run_one(rule: Rule) -> tuple[list[Finding], float]:
        started = time.perf_counter()
        kept = []
        for finding in rule.check(ctx):
            module = ctx.module(finding.path)
            if module is not None and module.suppressed(finding.rule, finding.line):
                continue
            kept.append(finding)
        return kept, time.perf_counter() - started

    findings: list[Finding] = []
    if jobs > 1 and len(rules) > 1:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=jobs) as pool:
            results = list(pool.map(run_one, rules))
    else:
        results = [run_one(rule) for rule in rules]
    for rule, (kept, wall) in zip(rules, results):
        findings.extend(kept)
        if timings is not None:
            timings[rule.id] = wall
    pragma_errors: list[Finding] = []
    for module in ctx.modules:
        if module.parse_error:
            pragma_errors.append(
                Finding(
                    rule="GL000", path=module.relpath, line=1,
                    message=module.parse_error,
                )
            )
        for pragma in module.malformed_pragmas():
            pragma_errors.append(
                Finding(
                    rule="GL000",
                    path=module.relpath,
                    line=pragma.line,
                    message=(
                        "malformed graftlint pragma: `reason=` is required "
                        "(a suppression must document itself); this pragma "
                        "suppresses nothing"
                    ),
                )
            )
    key = lambda f: (f.path, f.line, f.rule)  # noqa: E731
    return sorted(findings, key=key), sorted(pragma_errors, key=key)
