"""graftlint — JAX/TPU-aware static analysis for the repo's own invariants.

The codebase carries three load-bearing invariant families that ordinary
linters cannot see:

- **hot-path purity** — nothing reachable from a ``jax.jit``/``pallas_call``
  entry point may synchronise with the host (``.item()``, ``np.asarray``,
  ``jax.device_get``, ``block_until_ready``): one stray host sync inside the
  decode loop serialises the TPU and the p50 SLO dies silently;
- **deadline propagation** (PR 1, utils/deadline.py) — every blocking
  external call on the analysis path must spend a budget, not block forever;
- **lock discipline** — operator/memory state shared between watcher threads
  and the pipeline must only be touched under its guarding lock.

``python -m operator_tpu.analysis`` runs every registered rule over the
repo, honours inline ``# graftlint: disable=GLxxx reason=...`` pragmas and a
committed baseline (``analysis-baseline.json``) of grandfathered findings,
and exits non-zero on anything new — the CI gate (docs/ANALYSIS.md).

This package imports neither jax nor the runtime modules it analyses (pure
``ast``), so the gate runs on any box in milliseconds.
"""

from .baseline import Baseline, load_baseline, write_baseline
from .core import AnalysisContext, Finding, ModuleSource, Rule
from .rules import ALL_RULES, rules_by_id
from .runner import run_analysis

__all__ = [
    "ALL_RULES",
    "AnalysisContext",
    "Baseline",
    "Finding",
    "ModuleSource",
    "Rule",
    "load_baseline",
    "rules_by_id",
    "run_analysis",
    "write_baseline",
]
