"""CLI: ``python -m operator_tpu.analysis [--baseline FILE] [paths...]``.

Exit codes: 0 = clean (every finding baselined or suppressed), 1 = new
findings (or malformed pragmas), 2 = usage error.  ``--fix`` does not exist
by design — every finding here is a semantic invariant whose correct repair
needs a human decision (which branch of the degradation ladder, which lock,
which budget slice); a mechanical rewrite would hide exactly the thinking
the rule exists to force.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from .baseline import Baseline, load_baseline, write_baseline
from .rules import ALL_RULES, rules_by_id
from .runner import changed_paths, collect_context, run_analysis


def _github_line(finding) -> str:
    """One GitHub workflow-command annotation per finding: the Actions
    runner turns these into inline PR annotations at file:line."""
    # workflow-command property values: escape %, then CR/LF
    message = (
        finding.message.replace("%", "%25")
        .replace("\r", "%0D")
        .replace("\n", "%0A")
    )
    title = finding.rule + (f" {finding.symbol}" if finding.symbol else "")
    title = title.replace("%", "%25").replace(",", "%2C").replace("::", "")
    return (
        f"::error file={finding.path},line={finding.line},"
        f"title={title}::{message}"
    )


def _sarif_report(findings, rules) -> dict:
    """SARIF 2.1.0 document for GitHub code scanning: one run, one result
    per finding, rule metadata from the catalogue.  Deterministic field
    order so artifact diffs are meaningful."""
    known = {rule.id for rule in rules}
    extra = sorted({f.rule for f in findings} - known)  # GL000 pragma/parse
    driver_rules = [
        {
            "id": rule.id,
            "name": rule.name,
            "shortDescription": {"text": rule.description or rule.name},
            "helpUri": "https://example.invalid/docs/ANALYSIS.md",
        }
        for rule in rules
    ] + [
        {
            "id": rule_id,
            "name": "framework",
            "shortDescription": {
                "text": "parse error or malformed graftlint pragma"
            },
        }
        for rule_id in extra
    ]
    results = [
        {
            "ruleId": finding.rule,
            "level": "error",
            "message": {
                "text": finding.message
                + (f" [{finding.symbol}]" if finding.symbol else "")
            },
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {
                            "uri": finding.path,
                            "uriBaseId": "%SRCROOT%",
                        },
                        "region": {"startLine": max(finding.line, 1)},
                    }
                }
            ],
        }
        for finding in findings
    ]
    return {
        "$schema": (
            "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
            "master/Schemata/sarif-schema-2.1.0.json"
        ),
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "graftlint",
                        "informationUri": (
                            "https://example.invalid/docs/ANALYSIS.md"
                        ),
                        "rules": driver_rules,
                    }
                },
                "results": results,
            }
        ],
    }


def _detect_root(start: Path) -> Path:
    """Nearest ancestor containing the package (or pyproject) — the repo
    root all finding paths are relative to."""
    current = start.resolve()
    for candidate in [current, *current.parents]:
        if (candidate / "operator_tpu").is_dir() or (
            candidate / "pyproject.toml"
        ).exists():
            return candidate
    return current


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m operator_tpu.analysis",
        description="graftlint: enforce the repo's hot-path, deadline, "
        "concurrency and generated-artifact invariants (docs/ANALYSIS.md)",
    )
    parser.add_argument(
        "paths", nargs="*", type=Path,
        help="files/directories to analyse (default: the repo root)",
    )
    parser.add_argument(
        "--root", type=Path, default=None,
        help="repo root for relative paths + project rules (default: "
        "auto-detected from cwd)",
    )
    parser.add_argument(
        "--baseline", type=Path, default=None,
        help="baseline JSON of grandfathered findings (analysis-baseline.json)",
    )
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="record the current findings into --baseline and exit 0",
    )
    parser.add_argument(
        "--rules", "--rule", default=None,
        help="comma-separated rule ids to run (default: all)",
    )
    parser.add_argument(
        "--format", choices=("text", "json", "github", "sarif"),
        default="text",
        help="github = workflow-command annotations (::error file=...) so "
        "CI findings land inline on the PR diff; sarif = SARIF 2.1.0 on "
        "stdout for the code-scanning upload",
    )
    parser.add_argument(
        "--jobs", type=int, default=1, metavar="N",
        help="run rules concurrently on N threads (parsed ASTs, symbol "
        "tables and the callgraph are shared through the per-run context "
        "memo; output is byte-identical to a serial run)",
    )
    parser.add_argument(
        "--seam-coverage", type=Path, default=None, metavar="FILE",
        help="write GL012's deterministic seam-coverage audit map (JSON) "
        "to FILE — requires GL012 in the run",
    )
    parser.add_argument(
        "--timings-budget", type=float, default=None, metavar="SECONDS",
        help="fail (exit 1) when total rule wall time exceeds SECONDS — "
        "CI asserts the full gate stays within budget",
    )
    parser.add_argument(
        "--changed-only", metavar="REF", default=None,
        help="lint only files differing from git REF (plus untracked) — "
        "the fast local pre-commit mode; repo-level artifact rules still "
        "check the whole tree",
    )
    parser.add_argument(
        "--timings", action="store_true",
        help="print per-rule wall time after the run",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalogue",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in ALL_RULES:
            print(f"{rule.id}  {rule.name}\n    {rule.description}")
        return 0

    try:
        rules = rules_by_id(
            [r.strip() for r in args.rules.split(",")] if args.rules else None
        )
    except KeyError as exc:
        print(exc.args[0], file=sys.stderr)
        return 2

    root = (args.root or _detect_root(Path.cwd())).resolve()
    paths = list(args.paths) if args.paths else None
    if args.changed_only is not None:
        if args.paths:
            print("--changed-only and explicit paths are mutually "
                  "exclusive", file=sys.stderr)
            return 2
        try:
            paths = changed_paths(root, args.changed_only)
        except ValueError as exc:
            print(exc, file=sys.stderr)
            return 2
        if not paths:
            print(
                f"graftlint: clean — no .py files differ from "
                f"{args.changed_only}"
            )
            return 0
    try:
        ctx = collect_context(root, paths)
    except (FileNotFoundError, ValueError) as exc:
        print(exc, file=sys.stderr)
        return 2
    timings: dict = {}
    findings, pragma_errors = run_analysis(
        ctx, rules, timings=timings, jobs=max(1, args.jobs)
    )

    if args.write_baseline:
        if args.baseline is None:
            print("--write-baseline requires --baseline", file=sys.stderr)
            return 2
        if args.rules or args.paths or args.changed_only:
            # a partial run writes a partial baseline, silently dropping
            # every other rule's grandfathered entries — refuse
            print(
                "--write-baseline records the FULL analysis; drop --rules/"
                "--changed-only/path arguments (a partial baseline would "
                "discard the other rules' grandfathered findings)",
                file=sys.stderr,
            )
            return 2
        write_baseline(args.baseline, findings)
        print(
            f"baseline written: {len(findings)} finding(s) -> {args.baseline}"
        )
        return 0

    baseline = Baseline()
    if args.baseline is not None:
        if not args.baseline.exists():
            # a moved/typo'd baseline must not dress grandfathered debt up
            # as new regressions — fail loudly like a typo'd source path
            print(f"no such baseline file: {args.baseline} (create one "
                  "with --write-baseline)", file=sys.stderr)
            return 2
        baseline = load_baseline(args.baseline)
    new, stale = baseline.filter(findings)
    # a partial run (--rules/paths) can only vouch for what it ran: an
    # entry for a rule that did not run is not stale, it is unchecked
    if args.rules:
        ran_rules = {rule.id for rule in rules}
        stale = [key for key in stale if key[0] in ran_rules]
    if args.paths or args.changed_only:
        analyzed = {m.relpath for m in ctx.modules}
        stale = [key for key in stale if key[1] in analyzed]
    new = pragma_errors + new

    if args.timings and args.format not in ("json", "sarif"):
        for rule in rules:
            print(f"timing: {rule.id}  {timings.get(rule.id, 0.0) * 1e3:8.1f} ms")

    if args.seam_coverage is not None:
        coverage = ctx.caches.get("seam_coverage")
        if coverage is None:
            print("--seam-coverage requires rule GL012 in the run",
                  file=sys.stderr)
            return 2
        args.seam_coverage.write_text(
            json.dumps(coverage, indent=2, sort_keys=True) + "\n",
            encoding="utf-8",
        )

    def emit() -> int:
        if args.format == "github":
            for finding in new:
                print(_github_line(finding))
            if new:
                print(
                    f"graftlint: {len(new)} finding(s) not in the baseline "
                    "(docs/ANALYSIS.md)"
                )
                return 1
            print(
                f"graftlint: clean — {len(ctx.modules)} file(s), "
                f"{len(rules)} rule(s)"
            )
            return 0

        if args.format == "sarif":
            # pure JSON on stdout (the upload artifact); the human
            # summary rides stderr
            print(json.dumps(_sarif_report(new, rules), indent=2))
            print(
                f"graftlint: {len(new)} finding(s) ({len(ctx.modules)} "
                f"file(s), {len(rules)} rule(s))",
                file=sys.stderr,
            )
            return 1 if new else 0

        if args.format == "json":
            print(json.dumps(
                {
                    "findings": [f.__dict__ for f in new],
                    "baselined": len(findings) - (len(new) - len(pragma_errors)),
                    "stale_baseline": [list(k) for k in stale],
                },
                indent=2,
            ))
            return 1 if new else 0

        for finding in new:
            print(finding.render())
        for rule, path, symbol, message in stale:
            sym = f" [{symbol}]" if symbol else ""
            print(
                f"note: stale baseline entry {rule} {path}{sym}: {message!r} "
                "no longer matches — remove it from the baseline"
            )
        if new:
            print(
                f"\ngraftlint: {len(new)} finding(s) not in the baseline "
                "(see docs/ANALYSIS.md; suppress deliberate exceptions with "
                "`# graftlint: disable=GLxxx reason=...`)"
            )
            return 1
        suppressed = len(findings) - len(new) + len(pragma_errors)
        print(
            f"graftlint: clean — {len(ctx.modules)} file(s), "
            f"{len(ALL_RULES) if not args.rules else len(rules)} rule(s), "
            f"{suppressed} baselined finding(s)"
        )
        return 0

    code = emit()
    total_wall = sum(timings.values())
    if args.timings_budget is not None and total_wall > args.timings_budget:
        print(
            f"graftlint: rule wall time {total_wall:.2f}s exceeds "
            f"--timings-budget {args.timings_budget:.2f}s — a rule grew "
            "quadratic pain; see the per-rule --timings breakdown",
            file=sys.stderr,
        )
        code = max(code, 1)
    return code


if __name__ == "__main__":
    sys.exit(main())
