"""GL013 — mesh-axis consistency for collectives and PartitionSpecs.

A collective that names an axis the mesh never declared
(``lax.psum(x, "model")`` under ``Mesh(..., ("dp", "fsdp", "tp"))``) and
a ``PartitionSpec`` naming a nonexistent mesh axis both pass every
single-device test and fail only at trace time on real multichip
hardware — the exact failure mode the mixed-program sharding port
(ROADMAP wave-deletion item) cannot afford to discover on a TPU pod.

Scope model (``ops/`` + ``parallel/`` + ``serving/``):

- **axis environment** — the set of axis names declared by the governing
  mesh context.  The nearest lexically-enclosing ``with Mesh(...)``
  (directly, or through a local ``m = Mesh(...)`` binding) shadows; with
  no enclosing mesh the module environment applies: the union of every
  ``Mesh(...)`` declaration in the module.  Axis tuples resolve through
  literals and module-level constants (``AXES``), following ``from x
  import y`` across the analysed set.  A module that declares NO mesh
  has an empty environment and is skipped — its specs are checked where
  a mesh is in scope (the rule verifies consistency, it does not demand
  a mesh).
- **collectives** — the ``lax.p*`` family plus ``all_gather`` /
  ``all_to_all`` / ``axis_index``, reached as ``lax.<f>`` /
  ``jax.lax.<f>`` or imported bare.  The checked axis comes from
  ``axis_name=`` or its positional slot; unresolvable (dynamic) axis
  expressions are skipped, not guessed.
- **PartitionSpec** — every string axis in a ``PartitionSpec``/``P``
  call (including inside tuple entries for multi-axis sharding) must be
  declared by the governing environment.
"""

from __future__ import annotations

import ast
from typing import Optional

from ..callgraph import attr_chain
from ..core import AnalysisContext, Finding, ModuleSource, Rule

#: lax collectives that are NOT spelled ``p*``
_COLLECTIVES_EXTRA = {"all_gather", "all_to_all", "axis_index", "axis_size"}

#: lax ``p*`` family members (explicit list: ``lax.pad``/``lax.pow`` are
#: not collectives, so a bare ``p`` prefix match would flood)
_P_FAMILY = {
    "psum", "pmean", "pmax", "pmin", "ppermute", "pshuffle",
    "psum_scatter", "pbroadcast", "pdot", "pgather",
}

_COLLECTIVES = _P_FAMILY | _COLLECTIVES_EXTRA

#: collectives whose axis_name is the FIRST positional argument
_AXIS_FIRST = {"axis_index", "axis_size"}


def _is_lax_root(chain: list[str]) -> bool:
    return chain[:-1] in (["lax"], ["jax", "lax"])


class MeshAxisConsistency(Rule):
    id = "GL013"
    name = "mesh-axis-consistency"
    description = (
        "every collective (lax.p* family, all_gather, axis_index) must name "
        "an axis declared by the governing Mesh/shard_map context, and every "
        "PartitionSpec axis must exist in that mesh — mistyped axes "
        "otherwise fail only on multichip hardware"
    )
    scope = (
        r"operator_tpu/ops/.*\.py$",
        r"operator_tpu/parallel/.*\.py$",
        r"operator_tpu/serving/.*\.py$",
    )

    def check(self, ctx: AnalysisContext) -> list[Finding]:
        modules = ctx.in_scope(self.scope)
        tables = ctx.symbol_tables(modules)
        findings: list[Finding] = []
        for module in modules:
            if module.tree is None:
                continue
            consts = self._module_constants(module)
            pspec_names = self._pspec_aliases(module)
            lax_imports = self._lax_imports(module)

            def resolve_axes(node: ast.AST) -> Optional[set[str]]:
                """Axis-name set denoted by an expression, None when not
                statically resolvable.  Follows module constants in this
                module and, through the import table, in siblings."""
                if isinstance(node, ast.Constant):
                    return {node.value} if isinstance(node.value, str) else None
                if isinstance(node, (ast.Tuple, ast.List)):
                    out: set[str] = set()
                    for element in node.elts:
                        got = resolve_axes(element)
                        if got is None:
                            return None
                        out |= got
                    return out
                if isinstance(node, ast.Name):
                    if node.id in consts:
                        return consts[node.id]
                    imported = tables.imports.get(module.relpath, {}).get(node.id)
                    if imported is not None:
                        rel, orig = imported
                        other = ctx.module(rel)
                        if other is not None:
                            return self._module_constants(other).get(orig)
                return None

            def mesh_axes(call: ast.Call) -> Optional[set[str]]:
                """Axes a ``Mesh(devices, axis_names)`` call declares."""
                chain = attr_chain(call.func)
                if not chain or chain[-1] != "Mesh":
                    return None
                axis_arg: Optional[ast.AST] = None
                if len(call.args) > 1:
                    axis_arg = call.args[1]
                for kw in call.keywords:
                    if kw.arg == "axis_names":
                        axis_arg = kw.value
                if axis_arg is None:
                    return None
                return resolve_axes(axis_arg)

            # module environment: union of every Mesh declaration
            module_env: set[str] = set()
            for node in ast.walk(module.tree):
                if isinstance(node, ast.Call):
                    got = mesh_axes(node)
                    if got:
                        module_env |= got

            def local_mesh_binding(name: str, site: ast.AST) -> Optional[set[str]]:
                """Axes of ``name`` when bound by ``name = Mesh(...)`` in
                an enclosing def or at module level."""
                scope = getattr(site, "_graftlint_parent", None)
                while scope is not None:
                    if isinstance(
                        scope,
                        (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module),
                    ):
                        for child in ast.walk(scope):
                            if not isinstance(child, ast.Assign):
                                continue
                            if not any(
                                isinstance(t, ast.Name) and t.id == name
                                for t in child.targets
                            ):
                                continue
                            if isinstance(child.value, ast.Call):
                                got = mesh_axes(child.value)
                                if got:
                                    return got
                    scope = getattr(scope, "_graftlint_parent", None)
                return None

            def check_axis_names(call, names, env, what):
                for axis in sorted(names):
                    if axis not in env:
                        declared = ", ".join(sorted(env))
                        findings.append(
                            self.finding(
                                module, call,
                                f"{what} names axis '{axis}' not declared "
                                f"by the governing mesh (axes: {declared}) "
                                "— a mistyped axis fails only at trace "
                                "time on multichip hardware",
                            )
                        )

            def walk(node: ast.AST, env: set[str]) -> None:
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        expr = item.context_expr
                        got = None
                        if isinstance(expr, ast.Call):
                            got = mesh_axes(expr)
                        elif isinstance(expr, ast.Name):
                            got = local_mesh_binding(expr.id, node)
                        if got:
                            # nearest mesh context SHADOWS — an inner
                            # with Mesh(...) redefines the axis world
                            env = got
                if isinstance(node, ast.Call):
                    self._check_call(
                        node, env, pspec_names, lax_imports,
                        resolve_axes, check_axis_names,
                    )
                for child in ast.iter_child_nodes(node):
                    walk(child, env)

            walk(module.tree, module_env)
        return findings

    # -- per-call check -------------------------------------------------
    def _check_call(
        self, call, env, pspec_names, lax_imports, resolve_axes, report
    ) -> None:
        chain = attr_chain(call.func)
        name = chain[-1] if chain else ""
        # collective?
        is_collective = name in _COLLECTIVES and (
            (len(chain) == 1 and name in lax_imports) or _is_lax_root(chain)
        )
        if is_collective and env:
            slot = 0 if name in _AXIS_FIRST else 1
            axis_arg = call.args[slot] if len(call.args) > slot else None
            for kw in call.keywords:
                if kw.arg == "axis_name":
                    axis_arg = kw.value
            axes = resolve_axes(axis_arg) if axis_arg is not None else None
            if axes:
                report(call, axes, env, f"collective {name}(...)")
            return
        # PartitionSpec?
        if env and (
            name in ("PartitionSpec",)
            or (len(chain) == 1 and name in pspec_names)
        ):
            axes: set[str] = set()
            for arg in call.args:
                got = resolve_axes(arg)
                if got:
                    axes |= got
            if axes:
                report(call, axes, env, "PartitionSpec")

    # -- per-module tables ----------------------------------------------
    @staticmethod
    def _module_constants(module: ModuleSource) -> dict[str, set[str]]:
        """Module-level ``NAME = ("a", "b")`` / ``NAME = "a"`` string
        constants — how ``AXES`` reaches ``Mesh(array, AXES)``."""
        out: dict[str, set[str]] = {}
        if module.tree is None:
            return out
        for node in module.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            value = node.value
            axes: Optional[set[str]] = None
            if isinstance(value, ast.Constant) and isinstance(value.value, str):
                axes = {value.value}
            elif isinstance(value, (ast.Tuple, ast.List)) and all(
                isinstance(e, ast.Constant) and isinstance(e.value, str)
                for e in value.elts
            ):
                axes = {e.value for e in value.elts}
            if axes is None:
                continue
            for target in node.targets:
                if isinstance(target, ast.Name):
                    out[target.id] = axes
        return out

    @staticmethod
    def _pspec_aliases(module: ModuleSource) -> set[str]:
        """Local names bound to ``jax.sharding.PartitionSpec`` (the repo
        convention is ``... import PartitionSpec as P``)."""
        names: set[str] = set()
        if module.tree is None:
            return names
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ImportFrom) and node.module and (
                node.module.endswith("sharding") or node.module == "jax"
            ):
                for alias in node.names:
                    if alias.name == "PartitionSpec":
                        names.add(alias.asname or alias.name)
        return names

    @staticmethod
    def _lax_imports(module: ModuleSource) -> set[str]:
        """Collective names imported bare from ``jax.lax``."""
        names: set[str] = set()
        if module.tree is None:
            return names
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ImportFrom) and node.module and (
                node.module == "jax.lax" or node.module.endswith(".lax")
            ):
                for alias in node.names:
                    if alias.name in _COLLECTIVES:
                        names.add(alias.asname or alias.name)
        return names
