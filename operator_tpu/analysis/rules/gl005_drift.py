"""GL005 — generated artifacts must match their source of truth.

Two drift families, both of which have bitten operators of systems like
this one in the field:

- **CRD manifests**: ``deploy/crds/podmortem-crds.yaml`` is generated from
  ``operator_tpu/schema/crdgen.py``.  A schema edit without a regenerated
  manifest means the apiserver validates against YESTERDAY's API — specs
  the code handles get rejected at admission, or worse, admitted fields
  get silently dropped.
- **metric documentation**: every ``podmortem_*`` metric the code can emit
  must appear in the docs (docs/METRICS.md) — an operator alerting on an
  undocumented counter name is debugging blind.

The metric half absorbed ``scripts/check_metric_docs.py``; the shim is
deleted — CI runs ``python -m operator_tpu.analysis --rule GL005``
directly (same scan via :func:`emitted_metrics`/:func:`documented_text`,
same verdict).
"""

from __future__ import annotations

import pathlib
import re

from ..core import AnalysisContext, Finding, Rule

#: every string literal inside an .incr(...) argument list (conditional
#: expressions like incr("a" if x else "b") emit BOTH names)
INCR_CALL = re.compile(r"\.incr\(([^)]*)\)", re.DOTALL)
#: histogram observations: an .observe(<name>, value) call whose name
#: literal carries a unit suffix renders as the podmortem_<name> family —
#: only unit-suffixed strings count, so the step clock's kind= literals
#: ("decode", "mixed") never read as metrics
OBSERVE_CALL = re.compile(r"\.observe\(([^)]*)\)", re.DOTALL)
UNIT_SUFFIXES = ("_milliseconds", "_seconds", "_bytes")
STRING = re.compile(r"[\"']([a-z0-9_]+)[\"']")
#: fully-formed metric names in code (the stage-summary constant); a bare
#: "podmortem_..." dict key without a metric suffix is not a metric
LITERAL = re.compile(
    r"[\"'](podmortem_[a-z0-9_]+_total|podmortem_[a-z0-9_]+_milliseconds)[\"']"
)

CRD_MANIFEST = "deploy/crds/podmortem-crds.yaml"


def emitted_metrics(root: pathlib.Path) -> set[str]:
    """Every ``podmortem_*`` metric name the code under ``root`` can emit
    (the scan the old ``check_metric_docs`` script always ran, verbatim)."""
    metrics: set[str] = set()
    for path in (root / "operator_tpu").rglob("*.py"):
        text = path.read_text(encoding="utf-8", errors="replace")
        for args in INCR_CALL.findall(text):
            # the labels= kwarg of a labeled counter carries label KEYS
            # ("reason", "slo_class"), not metric names — stop before it
            for name in STRING.findall(args.split("labels=")[0]):
                metrics.add(f"podmortem_{name}_total")
        for args in OBSERVE_CALL.findall(text):
            for name in STRING.findall(args):
                if name.endswith(UNIT_SUFFIXES):
                    metrics.add(f"podmortem_{name}")
        for name in LITERAL.findall(text):
            metrics.add(name)
    return metrics


def documented_text(root: pathlib.Path) -> str:
    blobs = []
    for path in sorted((root / "docs").glob("*.md")):
        blobs.append(path.read_text(encoding="utf-8", errors="replace"))
    readme = root / "README.md"
    if readme.exists():
        blobs.append(readme.read_text(encoding="utf-8", errors="replace"))
    return "\n".join(blobs)


def undocumented_metrics(root: pathlib.Path) -> list[str]:
    docs = documented_text(root)
    return sorted(m for m in emitted_metrics(root) if m not in docs)


class GeneratedArtifactDrift(Rule):
    id = "GL005"
    name = "generated-artifact-drift"
    description = (
        "deploy/crds/podmortem-crds.yaml must equal schema/crdgen.py output, "
        "and every emitted podmortem_* metric must be documented under docs/"
    )
    scope = (CRD_MANIFEST.replace(".", r"\.") + "$", r"docs/METRICS\.md$")

    def check(self, ctx: AnalysisContext) -> list[Finding]:
        findings: list[Finding] = []
        findings.extend(self._check_crds(ctx))
        for metric in undocumented_metrics(ctx.root):
            findings.append(
                Finding(
                    rule=self.id,
                    path="docs/METRICS.md",
                    line=1,
                    message=(
                        f"emitted metric {metric} is not documented anywhere "
                        "under docs/ or README.md"
                    ),
                    symbol="metrics",
                )
            )
        return findings

    def _check_crds(self, ctx: AnalysisContext) -> list[Finding]:
        if not (ctx.root / "operator_tpu/schema/crdgen.py").exists():
            # fixture/partial tree without the generator: nothing to compare
            return []
        manifest = ctx.root / CRD_MANIFEST
        if not manifest.exists():
            return [
                Finding(
                    rule=self.id, path=CRD_MANIFEST, line=1, symbol="crds",
                    message=(
                        f"{CRD_MANIFEST} is missing — regenerate with "
                        "`python -m operator_tpu.schema.crdgen > "
                        f"{CRD_MANIFEST}`"
                    ),
                )
            ]
        try:
            # one comparison, shared with `python -m operator_tpu.schema.
            # crdgen --check` so the regen loop and the CI gate can never
            # disagree about what counts as drift
            from ...schema.crdgen import check_manifest
        except Exception as exc:  # yaml missing, import cycle, ...
            return [
                Finding(
                    rule=self.id, path=CRD_MANIFEST, line=1, symbol="crds",
                    message=f"cannot render CRDs to compare: {exc}",
                )
            ]
        if not check_manifest(str(manifest)):
            return [
                Finding(
                    rule=self.id, path=CRD_MANIFEST, line=1, symbol="crds",
                    message=(
                        f"{CRD_MANIFEST} drifted from schema/crdgen.py — "
                        "regenerate with `python -m operator_tpu.schema."
                        f"crdgen > {CRD_MANIFEST}`"
                    ),
                )
            ]
        return []
