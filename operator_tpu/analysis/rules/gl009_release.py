"""GL009: every slot/page acquire must reach a release on all paths.

The KV economy (PR 10) hands out finite resources — allocator pages,
KV-store block refs, transfer lanes, scheduler slots.  A page acquired
and then dropped on an early return or an exception path is not a
crash: it is a slow capacity leak that shows up days later as admission
stalls with a healthy-looking fleet.  This rule proves, per function,
that every tracked acquire is discharged on every syntactic path.

The CFG model (deliberately small — see docs/ANALYSIS.md#gl009):

- **Obligation**: ``name = <recv>.allocate(...)`` / ``<recv>.acquire(...)``
  with a single Name target, unwrapping ``await`` and a trailing
  subscript (``page = g.allocator.allocate(1)[0]``).  An acquire whose
  result is NOT bound to a name is untracked (the codebase uses that
  shape only for refcount bumps whose release is owned elsewhere).
- **Discharge**: any later load of the name — a ``release(pages)`` call,
  an ownership transfer into a row/struct (``_Row(..., pages=grant)``),
  a return of the handle.  Coarse on purpose: the rule's job is the
  *dropped* handle, not auditing what the consumer does with it.
- **Paths**: ``if``/``elif``/``else`` branch states merge by union (a
  handle still live on either arm is still an obligation);
  ``for``/``while`` bodies walk once inline; ``with`` walks inline.
  ``try`` bodies walk with every name mentioned in a handler or
  ``finally`` marked *protected* (the handler/finally is the release
  path — the engine's ``except BaseException: release(pages); raise``
  idiom); handlers then walk from the try-entry state.
- **Flag points**: a ``return`` leaving a live, unprotected handle that
  the return value does not carry ("early-return leak"); a ``raise``
  leaving one ("void-in-flight leak" — the in-flight handle dies with
  the exception); and function end.

Part B, same economy from the durability side: append-mode ``open``
(``"a"``/``"ab"``/``"a+"``) anywhere outside ``utils/journal.py`` is a
finding — every durable append must ride the Journal (fsync policy,
torn-tail recovery, writer-thread offload) instead of re-growing ad-hoc
append files the resume/compaction machinery cannot see.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Optional

from ..callgraph import DEF_NODES, attr_chain, iter_scope
from ..core import AnalysisContext, Finding, ModuleSource, Rule

#: method names whose bound result is a tracked resource handle
_ACQUIRE_METHODS = {"allocate", "acquire"}
#: modules under the intraprocedural CFG pass (the resource economy)
_CFG_SCOPE = (
    "operator_tpu/serving/sched/",
    "operator_tpu/serving/kvstore.py",
    "operator_tpu/serving/engine.py",
    "operator_tpu/ops/kv_transfer.py",
    # serverless-fleet arc (PR 17): ring membership and scale ticks hold
    # leases/guards whose early-return paths must discharge them too
    "operator_tpu/router/discovery.py",
    "operator_tpu/operator/autoscale.py",
    # fleet KV fabric (ISSUE 19): host-pool page adoption and fetch
    # bookkeeping must discharge what they acquire on every exit path
    "operator_tpu/fabric/",
)


def _acquire_target(stmt: ast.stmt) -> Optional[tuple[str, ast.Call]]:
    """``name`` and the acquire call when ``stmt`` binds one, else None."""
    if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
        return None
    target = stmt.targets[0]
    if not isinstance(target, ast.Name):
        return None
    value = stmt.value
    if isinstance(value, ast.Await):
        value = value.value
    if isinstance(value, ast.Subscript):
        value = value.value
    if (
        isinstance(value, ast.Call)
        and isinstance(value.func, ast.Attribute)
        and value.func.attr in _ACQUIRE_METHODS
    ):
        return target.id, value
    return None


def _loaded_names(node: ast.AST) -> set[str]:
    return {
        sub.id
        for sub in ast.walk(node)
        if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load)
    }


@dataclass(frozen=True)
class _Obligation:
    name: str
    line: int
    call: str  # rendered acquire expression, for the message


class _Walker:
    """One function's path walk.  ``live`` maps name -> obligation."""

    def __init__(self, rule: "ResourceReleaseRule", module: ModuleSource):
        self.rule = rule
        self.module = module
        self.leaks: dict[tuple[str, int], tuple[ast.AST, str]] = {}

    def walk(self, body: list[ast.stmt]) -> None:
        live = self._block(body, {}, protected=frozenset())
        for ob in live.values():
            self._leak(
                ob,
                body[-1],
                "still live at function end — no path releases it",
            )

    # -- the walk -------------------------------------------------------
    def _block(
        self,
        stmts: list[ast.stmt],
        live: dict[str, _Obligation],
        protected: frozenset,
    ) -> dict[str, _Obligation]:
        live = dict(live)
        for stmt in stmts:
            live = self._stmt(stmt, live, protected)
        return live

    def _discharge(
        self, node: ast.AST, live: dict[str, _Obligation],
        skip: Optional[str] = None,
    ) -> None:
        for name in _loaded_names(node):
            if name != skip:
                live.pop(name, None)

    def _stmt(
        self,
        stmt: ast.stmt,
        live: dict[str, _Obligation],
        protected: frozenset,
    ) -> dict[str, _Obligation]:
        acquired = _acquire_target(stmt)
        if acquired is not None:
            name, call = acquired
            # loads elsewhere in the SAME statement (the receiver) are
            # not a discharge of the new handle
            self._discharge(stmt, live, skip=name)
            live[name] = _Obligation(
                name=name, line=stmt.lineno,
                call=ast.unparse(call.func),
            )
            return live
        if isinstance(stmt, ast.Return):
            carried = _loaded_names(stmt.value) if stmt.value else set()
            for name, ob in list(live.items()):
                if name in carried or name in protected:
                    continue
                self._leak(
                    ob, stmt,
                    "dropped on early return — release (or transfer) it "
                    "before this return",
                )
            return {}
        if isinstance(stmt, ast.Raise):
            mentioned = _loaded_names(stmt)
            for name, ob in list(live.items()):
                if name in mentioned or name in protected:
                    continue
                self._leak(
                    ob, stmt,
                    "void-in-flight: still held when this raise unwinds — "
                    "release in an except/finally before re-raising",
                )
            return {}
        if isinstance(stmt, ast.If):
            self._discharge(stmt.test, live)
            then_live = self._block(stmt.body, live, protected)
            else_live = self._block(stmt.orelse, live, protected)
            return {**then_live, **else_live}
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._discharge(stmt.iter, live)
            body_live = self._block(stmt.body, live, protected)
            body_live = self._block(stmt.orelse, body_live, protected)
            return body_live
        if isinstance(stmt, ast.While):
            self._discharge(stmt.test, live)
            return self._block(stmt.body, live, protected)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._discharge(item.context_expr, live)
            return self._block(stmt.body, live, protected)
        if isinstance(stmt, ast.Try):
            cleanup: set[str] = set()
            for handler in stmt.handlers:
                cleanup |= _loaded_names(ast.Module(handler.body, []))
            cleanup |= _loaded_names(ast.Module(stmt.finalbody, []))
            entry = dict(live)
            body_live = self._block(
                stmt.body, live, protected | frozenset(cleanup)
            )
            for handler in stmt.handlers:
                self._block(handler.body, entry, protected)
            body_live = self._block(stmt.orelse, body_live, protected)
            return self._block(stmt.finalbody, body_live, protected)
        if isinstance(stmt, DEF_NODES) or isinstance(stmt, ast.ClassDef):
            return live  # nested scope: its own walk
        # plain statement: loads discharge
        self._discharge(stmt, live)
        return live

    def _leak(self, ob: _Obligation, at: ast.AST, why: str) -> None:
        key = (ob.name, ob.line)
        if key in self.leaks:
            return
        self.leaks[key] = (
            at,
            f"resource `{ob.name}` from `{ob.call}(...)` (line {ob.line}) "
            f"{why}",
        )


class ResourceReleaseRule(Rule):
    id = "GL009"
    name = "resource-release"
    description = (
        "every bound allocator/lane acquire in the KV economy must reach "
        "a release or ownership transfer on all paths (early returns, "
        "raises, function end); durable append-mode open() outside "
        "utils/journal.py must go through Journal"
    )
    scope = (r"operator_tpu/.*\.py$",)

    def check(self, ctx: AnalysisContext) -> list[Finding]:
        findings: list[Finding] = []
        for module in ctx.in_scope(self.scope):
            if module.tree is None:
                continue
            if any(module.relpath.startswith(p) or module.relpath == p
                   for p in _CFG_SCOPE):
                findings.extend(self._check_cfg(module))
            if module.relpath != "operator_tpu/utils/journal.py":
                findings.extend(self._check_append_open(module))
        return findings

    def _check_cfg(self, module: ModuleSource) -> list[Finding]:
        findings: list[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, DEF_NODES):
                continue
            walker = _Walker(self, module)
            walker.walk(node.body)
            for at, message in walker.leaks.values():
                findings.append(self.finding(module, at, message))
        return findings

    def _check_append_open(self, module: ModuleSource) -> list[Finding]:
        findings: list[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if not chain or chain[-1] not in ("open", "fdopen"):
                continue
            if chain == ["open"] or chain[-2:] == ["os", "fdopen"]:
                # open(path, mode) / os.fdopen(fd, mode)
                mode = node.args[1] if len(node.args) > 1 else None
            else:
                # <path-like>.open(mode)
                mode = node.args[0] if node.args else None
            for kw in node.keywords:
                if kw.arg == "mode":
                    mode = kw.value
            if (
                isinstance(mode, ast.Constant)
                and isinstance(mode.value, str)
                and mode.value.startswith("a")
            ):
                findings.append(self.finding(
                    module, node,
                    f"append-mode open({mode.value!r}) outside "
                    "utils/journal.py — durable appends must go through "
                    "Journal (fsync policy, torn-tail recovery, writer "
                    "thread); ad-hoc append files are invisible to resume/"
                    "compaction",
                ))
        return findings
