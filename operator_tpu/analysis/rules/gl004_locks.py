"""GL004 — lock discipline on state shared across threads.

Operator state is touched from several threads at once: the asyncio control
plane, ``asyncio.to_thread`` workers (pattern parse, incident recall/insert),
and the serving executor.  The codebase's convention is a per-object
``threading.Lock`` guarding a set of attributes; nothing enforced that the
set is guarded EVERYWHERE — one lock-free read of a dict that a worker
thread mutates is a data race that surfaces as a once-a-week corrupted
incident journal, not a test failure.

The rule infers, per class in ``operator/*.py`` and ``memory/*.py``:

- the class's **lock attributes** (assigned ``threading.Lock()`` /
  ``RLock()`` / ``Condition()`` / ``asyncio.Lock()``);
- the **guarded set**: attributes ever written inside a
  ``with self._lock:`` block (or inside a lock-held helper);
- **lock-held helpers**: methods whose every call site is under the lock
  (or in another lock-held helper) — plus anything named ``*_locked`` by
  convention;
- **init-only helpers**: methods reachable only from ``__init__``
  (construction happens-before publication; no other thread can see the
  object yet).

Every read or write of a guarded attribute outside a lock region in any
other method is a finding.  Deliberate lock-free snapshot reads (immutable
tuple swap + atomic reference read) are real patterns — mark them with
``# graftlint: disable=GL004 reason=...`` where reviewers can audit the
claim.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Optional

from ..core import AnalysisContext, Finding, ModuleSource, Rule

_DEF_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)
_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}
#: method names that mutate their container in place: ``self.x.append(...)``
#: is a WRITE to the guarded structure, not a read of the attribute
_MUTATOR_METHODS = {
    "append", "extend", "insert", "pop", "popitem", "clear", "update",
    "setdefault", "remove", "discard", "add", "move_to_end", "appendleft",
    "popleft", "sort", "reverse", "write",
}


def _self_attr(expr: ast.AST) -> Optional[str]:
    if (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "self"
    ):
        return expr.attr
    return None


@dataclass
class _Access:
    attr: str
    node: ast.AST
    is_write: bool
    under_lock: bool
    method: str


@dataclass
class _MethodInfo:
    node: ast.AST
    name: str
    accesses: list[_Access] = field(default_factory=list)
    #: self.method() call sites: (callee name, under_lock)
    calls: list[tuple[str, bool]] = field(default_factory=list)


class LockDiscipline(Rule):
    id = "GL004"
    name = "lock-discipline"
    description = (
        "an attribute ever written under a class's threading.Lock must "
        "never be read or written outside one (per-class guard-set "
        "inference; *_locked helpers and __init__-only paths exempt)"
    )
    scope = (
        r"operator_tpu/operator/.*\.py$",
        r"operator_tpu/memory/.*\.py$",
        # multi-replica data plane + the shared journal helper (ISSUE 6):
        # router health/ring state is mutated from concurrent dispatches,
        # and the journal's handle moves between caller and writer thread
        r"operator_tpu/router/.*\.py$",
        r"operator_tpu/utils/journal\.py$",
        # continuous-batching scheduler (ISSUE 7): row state is mutated
        # from the decode worker while submit paths enqueue/cancel —
        # any lock that grows here must follow the discipline
        r"operator_tpu/serving/sched/.*\.py$",
    )

    def check(self, ctx: AnalysisContext) -> list[Finding]:
        findings: list[Finding] = []
        for module in ctx.in_scope(self.scope):
            if module.tree is None:
                continue
            for node in module.tree.body:
                if isinstance(node, ast.ClassDef):
                    findings.extend(self._check_class(module, node))
        return findings

    # ------------------------------------------------------------------
    def _check_class(
        self, module: ModuleSource, cls: ast.ClassDef
    ) -> list[Finding]:
        lock_attrs = self._lock_attrs(cls)
        if not lock_attrs:
            return []
        methods: dict[str, _MethodInfo] = {}
        for item in cls.body:
            if isinstance(item, _DEF_NODES):
                methods[item.name] = self._scan_method(item, lock_attrs)

        init_only = self._closure(methods, seeds={"__init__"})
        init_only.discard("__init__")

        # lock-held helpers: fixpoint over "every non-init call site is
        # under the lock or inside another lock-held helper"
        lock_held = {
            name for name in methods if name.endswith("_locked")
        }
        changed = True
        while changed:
            changed = False
            for name, info in methods.items():
                if name in lock_held or name in init_only or name == "__init__":
                    continue
                sites = [
                    (caller, under)
                    for caller, m in methods.items()
                    for callee, under in m.calls
                    if callee == name and caller not in init_only
                    and caller != "__init__"
                ]
                if sites and all(
                    under or caller in lock_held for caller, under in sites
                ):
                    lock_held.add(name)
                    changed = True

        guarded: set[str] = set()
        for name, info in methods.items():
            for access in info.accesses:
                if access.is_write and (
                    access.under_lock or name in lock_held
                ):
                    guarded.add(access.attr)
        guarded -= lock_attrs

        findings: list[Finding] = []
        for name, info in methods.items():
            if name == "__init__" or name in init_only or name in lock_held:
                continue
            for access in info.accesses:
                if access.attr not in guarded or access.under_lock:
                    continue
                kind = "write to" if access.is_write else "read of"
                findings.append(
                    self.finding(
                        module, access.node,
                        f"unguarded {kind} self.{access.attr} — guarded by "
                        f"{cls.name}'s lock elsewhere (escape from the "
                        "inferred guard set)",
                    )
                )
        return findings

    # ------------------------------------------------------------------
    @staticmethod
    def _lock_attrs(cls: ast.ClassDef) -> set[str]:
        locks: set[str] = set()
        for node in ast.walk(cls):
            if not isinstance(node, ast.Assign):
                continue
            value = node.value
            if not isinstance(value, ast.Call):
                continue
            factory = (
                value.func.attr
                if isinstance(value.func, ast.Attribute)
                else value.func.id if isinstance(value.func, ast.Name) else ""
            )
            if factory not in _LOCK_FACTORIES:
                continue
            for target in node.targets:
                attr = _self_attr(target)
                if attr is not None:
                    locks.add(attr)
        return locks

    def _scan_method(
        self, method: ast.AST, lock_attrs: set[str]
    ) -> _MethodInfo:
        info = _MethodInfo(node=method, name=method.name)

        def visit(node: ast.AST, under_lock: bool) -> None:
            if isinstance(node, (ast.With, ast.AsyncWith)):
                locks_here = any(
                    _self_attr(item.context_expr) in lock_attrs
                    or (
                        isinstance(item.context_expr, ast.Call)
                        and _self_attr(item.context_expr.func) in lock_attrs
                    )
                    for item in node.items
                )
                for item in node.items:
                    visit(item.context_expr, under_lock)
                for child in node.body:
                    visit(child, under_lock or locks_here)
                return
            if isinstance(node, _DEF_NODES) and node is not method:
                # a closure outlives the statement that defined it: it may
                # run on another thread (executor.submit, callbacks) after
                # the lock is released, so its accesses count as LOCK-FREE
                # even when the def sits inside a `with self._lock:` block
                for child in node.body:
                    visit(child, False)
                return
            attr = _self_attr(node)
            if attr is not None and attr not in lock_attrs:
                parent = getattr(node, "_graftlint_parent", None)
                is_write = isinstance(node.ctx, (ast.Store, ast.Del))
                # `self.x[k] = v` and `self.x.append(...)` mutate through a
                # read-context attribute: count container mutation as write
                if not is_write and isinstance(parent, ast.Subscript):
                    is_write = isinstance(parent.ctx, (ast.Store, ast.Del))
                grandparent = getattr(parent, "_graftlint_parent", None)
                if (
                    not is_write
                    and isinstance(parent, ast.Attribute)
                    and parent.attr in _MUTATOR_METHODS
                    and isinstance(grandparent, ast.Call)
                    and grandparent.func is parent
                ):
                    is_write = True
                info.accesses.append(
                    _Access(attr, node, is_write, under_lock, method.name)
                )
            if isinstance(node, ast.Call):
                callee = _self_attr(node.func)
                if callee is not None:
                    info.calls.append((callee, under_lock))
            for child in ast.iter_child_nodes(node):
                visit(child, under_lock)

        for stmt in method.body:
            visit(stmt, False)
        return info

    # ------------------------------------------------------------------
    @staticmethod
    def _closure(
        methods: dict[str, _MethodInfo], seeds: set[str]
    ) -> set[str]:
        """Methods reachable ONLY from ``seeds`` (call-graph closure with
        the constraint that no non-seed, non-member method calls them)."""
        reachable = set(seeds)
        changed = True
        while changed:
            changed = False
            for name, info in methods.items():
                if name in reachable:
                    continue
                callers = [
                    caller
                    for caller, m in methods.items()
                    for callee, _ in m.calls
                    if callee == name
                ]
                if callers and all(c in reachable for c in callers):
                    reachable.add(name)
                    changed = True
        return reachable
