"""GL003 — deadline propagation on the analysis control plane.

PR 1 made the latency contract end-to-end: a :class:`~operator_tpu.utils.
deadline.Deadline` is born when a failure is claimed and every downstream
hop spends from it.  That contract decays one "quick" API call at a time —
an unbudgeted apiserver read in a helper blocks the pipeline for the TCP
stack's idea of forever, and the p50 SLO is gone with no test failing.

The rule: every blocking external call in the control-plane files
(``operator/pipeline.py``, ``providers.py``, ``patternsync.py``,
``kubeapi.py`` — and, since the flight-recorder PR widened the net to the
rest of the control plane, ``storage.py``, ``events.py``, ``watcher.py``,
``app.py``, plus the HA modules ``lease.py`` and ``claims.py``) must be
budget-bound **at the call itself**:

- wrapped in ``asyncio.wait_for(...)`` (the residue of a threaded
  Deadline — ``timeout=deadline.remaining()`` — is the idiom), or
- passing a ``timeout=`` / ``deadline=`` keyword.

A ``deadline`` parameter on the enclosing function is how the budget
arrives but is deliberately NOT sufficient on its own — an unspent
parameter bounds nothing, and the docs promise per-call enforcement.

"Blocking external" means: Kubernetes API verbs on an api handle
(``self.api.get(...)``, ``api.list(...)``), provider ``.generate(...)``,
subprocess ``.communicate()``, and ``urlopen``/opener HTTP calls.  Internal
awaits (queues, events, locks) are not external and are not flagged.
"""

from __future__ import annotations

import ast
from typing import Optional

from ..core import AnalysisContext, Finding, ModuleSource, Rule

_KUBE_OPS = {
    "get", "list", "list_rv", "create", "patch", "patch_status", "delete",
    "get_log", "watch",
}
_DEF_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


def _is_api_handle(expr: ast.AST) -> bool:
    """``api`` / ``self.api`` / ``self._api`` — the KubeApi handle shapes
    used across the control plane."""
    if isinstance(expr, ast.Name):
        return expr.id == "api"
    return (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "self"
        and expr.attr in ("api", "_api")
    )


def _terminal_name(func: ast.AST) -> str:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return ""


def external_call_label(call: ast.Call) -> Optional[str]:
    """Label a blocking-external-call site, or None.  Shared with GL012:
    the set of side-effecting sites the deadline rule budgets is exactly
    the set the chaos-seam auditor must prove faultable."""
    func = call.func
    if isinstance(func, ast.Attribute):
        if func.attr in _KUBE_OPS and _is_api_handle(func.value):
            return f"{ast.unparse(func)}(...)"
        if func.attr == "generate":
            return f"{ast.unparse(func)}(...)"
        if func.attr == "communicate":
            return f"{ast.unparse(func)}(...)"
        if func.attr in ("urlopen", "_opener"):
            return f"{ast.unparse(func)}(...)"
    elif isinstance(func, ast.Name) and func.id in ("urlopen", "_opener"):
        return f"{func.id}(...)"
    return None


class DeadlinePropagation(Rule):
    id = "GL003"
    name = "deadline-propagation"
    description = (
        "every blocking external call (kube API verb, provider generate, "
        "subprocess communicate, urlopen) must spend a budget at the call: "
        "asyncio.wait_for (typically on a threaded Deadline's remaining()) "
        "or a timeout=/deadline= keyword"
    )
    scope = (
        r"operator_tpu/operator/pipeline\.py$",
        r"operator_tpu/operator/providers\.py$",
        r"operator_tpu/operator/patternsync\.py$",
        r"operator_tpu/operator/kubeapi\.py$",
        # widened beyond the four analysis-path modules (the standing
        # ROADMAP item): the retry/backoff paths in storage and events,
        # the watch-adjacent lists in the watcher, and the app wiring all
        # make kube calls that must spend kube_call_timeout_s at the call
        r"operator_tpu/operator/storage\.py$",
        r"operator_tpu/operator/events\.py$",
        r"operator_tpu/operator/watcher\.py$",
        r"operator_tpu/operator/app\.py$",
        # survivable-control-plane modules (ISSUE 5): every lease
        # acquire/renew/release call and every claim-resume kube read must
        # spend kube_call_timeout_s AT the call — a wedged apiserver may
        # cost one bounded tick, never the renew loop (a leader that can't
        # step down is a split brain) or the takeover resume
        r"operator_tpu/operator/lease\.py$",
        r"operator_tpu/operator/claims\.py$",
        # multi-replica data plane (ISSUE 6): every routed dispatch must
        # spend its residual budget AT the attempt (asyncio.wait_for on
        # the deadline residue) — an unbudgeted replica call would let one
        # wedged replica eat the whole analysis envelope before failover;
        # the shared journal helper's IO rides the writer thread but any
        # external call it ever grows must be budget-bound too
        r"operator_tpu/router/.*\.py$",
        r"operator_tpu/utils/journal\.py$",
        # fleet KV fabric (ISSUE 19): every peer page fetch must spend its
        # residual budget AT the transport call — a wedged holder must
        # never cost more than the recompute the fetch was replacing
        r"operator_tpu/fabric/.*\.py$",
    )

    def check(self, ctx: AnalysisContext) -> list[Finding]:
        findings: list[Finding] = []
        for module in ctx.in_scope(self.scope):
            if module.tree is None:
                continue
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.Call):
                    continue
                label = self._external_call(node)
                if label is None:
                    continue
                if self._guarded(node):
                    continue
                findings.append(
                    self.finding(
                        module, node,
                        f"blocking external call {label} without a budget: "
                        "wrap in asyncio.wait_for on a threaded Deadline's "
                        "remaining() (utils/deadline.py), or pass timeout=",
                    )
                )
        return findings

    # -- matchers ------------------------------------------------------
    def _external_call(self, call: ast.Call) -> Optional[str]:
        return external_call_label(call)

    # -- guards --------------------------------------------------------
    @staticmethod
    def _is_literal_none(expr: ast.AST) -> bool:
        return isinstance(expr, ast.Constant) and expr.value is None

    def _guarded(self, call: ast.Call) -> bool:
        for kw in call.keywords:
            if kw.arg in ("timeout", "timeout_s", "deadline", "deadline_s"):
                # `timeout=None` is spelled like a budget and bounds
                # nothing; dynamic expressions (deadline.remaining(), a
                # conditional residue) are accepted
                return not self._is_literal_none(kw.value)
        node: Optional[ast.AST] = call
        while node is not None:
            node = getattr(node, "_graftlint_parent", None)
            if (
                isinstance(node, ast.Call)
                and node is not call
                and _terminal_name(node.func) == "wait_for"
            ):
                timeout: Optional[ast.AST] = None
                if len(node.args) > 1:
                    timeout = node.args[1]
                for kw in node.keywords:
                    if kw.arg == "timeout":
                        timeout = kw.value
                return timeout is not None and not self._is_literal_none(timeout)
        return False
