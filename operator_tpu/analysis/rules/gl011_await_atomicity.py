"""GL011: check-then-act across an await — the asyncio TOCTOU shape.

The serverless-fleet arc multiplied the number of concurrent coroutines
mutating shared object state: autoscaler ticks, endpoint-watch ring
membership, health-poll sweeps, leader cycles, supervisor restarts,
pipelined scheduler commits.  In asyncio nothing interleaves between two
statements — until one of them awaits.  The bug shape is always the
same: read shared state, suspend, then write something derived from the
stale read.  The write is not torn (the GIL is not the issue); it is
*based on a world that no longer exists* — a replica re-added after the
watch removed it, a slot double-committed, a cursor rewound.

The rule, per ``async def`` in ``operator/``, ``router/``, ``serving/``
and ``obs/`` (flow-sensitive, statement order respected):

- **Read**: a load of ``self.<attr>`` or of a module-level mutable
  container (dict/list/set literal at module scope, or a ``global``
  declaration).  Method lookups that are immediately called
  (``self._helper()``) are calls, not state reads.
- **Suspension**: a direct ``await``, an ``async for`` step, an ``async
  with`` enter, or a bare call whose interprocedural summary — computed
  on the shared callgraph tables, the same discipline as GL006's
  async-reachability — says it may await.  Function references handed
  to ``asyncio.to_thread`` / ``create_task`` / ``ensure_future`` /
  executors do not suspend the caller and are not summary edges.
- **Write**: an assignment / ``del`` / subscript store to the same
  location, or an in-place container mutation (``.append``/``.add``/
  ``.discard``/``.update`` ...).
- **Feeds**: the write mentions a local tainted by the stale read
  (including loop targets iterating a snapshot of the state), or sits
  inside an ``if``/``while``/``for`` region whose test/iterable read the
  state before the suspension — the classic check-then-act.

Sanctioned shapes that stay quiet by construction:

- **Revalidation**: re-reading the state after the await (a fresh
  membership check, a compare-before-set) clears staleness — the write
  is then based on the current world.
- **Held lock**: a write inside ``with``/``async with`` on an inferred
  lock attribute (GL004's guard-set discipline, plus ``asyncio.Lock``)
  is serialized against competing coroutines.
- **Atomic read-modify-write**: ``self.n += 1`` re-reads at the write
  with no interleaving point between — not a TOCTOU.
- **resourceVersion-guarded patches / done-guarded futures**: the guard
  re-reads (or the apiserver enforces) the current state at the act, so
  the data-flow condition never fires.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Optional

from ..callgraph import DEF_NODES, SymbolTables, attr_chain
from ..core import AnalysisContext, Finding, ModuleSource, Rule

#: in-place container mutations: a write to the attribute's structure
#: (mirrors GL004's set — Event.set()/clear() style signal methods are
#: deliberately absent: signaling is not state derivation)
_MUTATOR_METHODS = {
    "append", "extend", "insert", "pop", "popitem", "clear", "update",
    "setdefault", "remove", "discard", "add", "move_to_end", "appendleft",
    "popleft", "sort", "reverse",
}

#: lock constructors (threading + asyncio share the names)
_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore"}

#: wrappers whose function-valued / coroutine-valued arguments run
#: elsewhere: not a suspension of THIS coroutine, not a summary edge
_DETACH_CALLS = {"to_thread", "run_in_executor", "submit", "Thread",
                 "call_soon_threadsafe", "run_sync", "create_task",
                 "ensure_future"}

#: method names too generic for non-self interprocedural resolution
#: (same rationale as GL006)
_GENERIC_METHODS = {
    "append", "add", "acquire", "cancel", "clear", "close", "copy",
    "count", "discard", "done", "extend", "flush", "get", "index",
    "insert", "items", "join", "keys", "load", "open", "parse", "pop",
    "popleft", "put", "read", "record", "release", "remove", "result",
    "run", "send", "set", "sort", "start", "submit", "to_dict",
    "update", "values", "wait", "write",
}


def _self_attr(expr: ast.AST) -> Optional[str]:
    if (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "self"
    ):
        return expr.attr
    return None


def _module_mutable_globals(module: ModuleSource) -> set[str]:
    """Module-level names bound to mutable containers — shared state for
    every coroutine importing the module."""
    out: set[str] = set()
    _MUTABLE_CTORS = {"dict", "list", "set", "deque", "defaultdict",
                      "OrderedDict", "Counter"}
    for node in module.tree.body if module.tree else []:
        if not isinstance(node, ast.Assign):
            continue
        value = node.value
        mutable = isinstance(value, (ast.Dict, ast.List, ast.Set,
                                     ast.DictComp, ast.ListComp,
                                     ast.SetComp))
        if isinstance(value, ast.Call):
            name = (value.func.id if isinstance(value.func, ast.Name)
                    else value.func.attr if isinstance(value.func, ast.Attribute)
                    else "")
            mutable = name in _MUTABLE_CTORS
        if not mutable:
            continue
        for target in node.targets:
            if isinstance(target, ast.Name):
                out.add(target.id)
    return out


def _lock_names(module: ModuleSource, cls: Optional[ast.ClassDef]) -> set[str]:
    """Keys recognised as locks in ``with``/``async with`` items:
    ``self.<attr>`` assigned a Lock factory in the class, plus
    module-level lock names."""
    locks: set[str] = set()

    def factory_name(value: ast.AST) -> str:
        if not isinstance(value, ast.Call):
            return ""
        func = value.func
        if isinstance(func, ast.Attribute):
            return func.attr
        if isinstance(func, ast.Name):
            return func.id
        return ""

    if cls is not None:
        for node in ast.walk(cls):
            if not isinstance(node, ast.Assign):
                continue
            if factory_name(node.value) in _LOCK_FACTORIES:
                for target in node.targets:
                    attr = _self_attr(target)
                    if attr is not None:
                        locks.add(f"self.{attr}")
    for node in module.tree.body if module.tree else []:
        if isinstance(node, ast.Assign) and factory_name(node.value) in _LOCK_FACTORIES:
            for target in node.targets:
                if isinstance(target, ast.Name):
                    locks.add(target.id)
    return locks


def _owner_class(node: ast.AST) -> Optional[ast.ClassDef]:
    parent = getattr(node, "_graftlint_parent", None)
    while parent is not None:
        if isinstance(parent, ast.ClassDef):
            return parent
        parent = getattr(parent, "_graftlint_parent", None)
    return None


@dataclass
class _KeyState:
    """One shared location's history on the current path."""

    read_line: int
    stale_line: Optional[int] = None  # suspension line; None = fresh

    @property
    def stale(self) -> bool:
        return self.stale_line is not None


class _FnWalker:
    """Flow walk of ONE async def body, statement order respected.

    ``state`` maps shared keys (``self.x`` / global name) to their
    read/staleness; ``taint`` maps local names to the (key, read line)
    provenance of the shared reads that produced them; ``regions`` is the
    stack of enclosing branch/loop tests' shared reads (control
    dependence)."""

    def __init__(
        self,
        rule: "AwaitAtomicityRule",
        module: ModuleSource,
        fn: ast.AST,
        globals_: set[str],
        locks: set[str],
        may_await: "set[int]",
        tables: SymbolTables,
    ) -> None:
        self.rule = rule
        self.module = module
        self.fn = fn
        self.globals = globals_
        self.locks = locks
        self.may_await = may_await
        self.tables = tables
        self.state: dict[str, _KeyState] = {}
        self.taint: dict[str, set[tuple[str, int]]] = {}
        self.findings: dict[tuple[str, int], Finding] = {}

    # -- event primitives ---------------------------------------------
    def _suspend(self, line: int) -> None:
        for st in self.state.values():
            if st.stale_line is None:
                st.stale_line = line

    def _read(self, key: str, line: int) -> None:
        self.state[key] = _KeyState(read_line=line)

    def _write(
        self,
        key: str,
        node: ast.AST,
        stmt_locals: set[str],
        regions: list[dict[str, int]],
        under_lock: bool,
    ) -> None:
        st = self.state.get(key)
        if st is None or not st.stale or under_lock:
            return
        dependent = False
        for name in stmt_locals:
            for origin_key, _line in self.taint.get(name, ()):
                if origin_key == key:
                    dependent = True
        if not dependent:
            for region in regions:
                if key in region:
                    dependent = True
                    break
        if not dependent:
            return
        ident = (key, st.read_line)
        if ident in self.findings:
            return
        self.findings[ident] = self.rule.finding(
            self.module, node,
            f"`{key}` read at line {st.read_line} feeds this write across a "
            f"suspension point (line {st.stale_line}) — check-then-act is "
            "not atomic across an await: re-read/validate the state after "
            "the await, or hold the guarding lock across both",
        )

    # -- expression walking (eval order, own scope only) ---------------
    def _key_of(self, node: ast.AST) -> Optional[str]:
        attr = _self_attr(node)
        if attr is not None:
            return f"self.{attr}"
        if isinstance(node, ast.Name) and node.id in self.globals:
            return node.id
        return None

    def _walk_expr(self, node: ast.AST) -> None:
        """Record reads/suspensions of an expression tree in (approximate)
        evaluation order.  Does not descend into nested def/lambda bodies
        (their execution is deferred to their own call)."""
        if node is None:
            return
        if isinstance(node, (*DEF_NODES, ast.Lambda)):
            return
        if isinstance(node, ast.Await):
            self._walk_expr(node.value)
            self._suspend(node.lineno)
            return
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            detached = bool(chain) and chain[-1] in _DETACH_CALLS
            # receiver expression of the call target still evaluates
            if isinstance(node.func, ast.Attribute):
                key = self._key_of(node.func)
                # a method/attr lookup that is immediately called is a
                # call, not a state read — unless it mutates (handled at
                # the statement level) or feeds detach wrappers
                if key is None:
                    self._walk_expr(node.func.value)
            if not detached:
                for arg in node.args:
                    self._walk_expr(arg)
                for kw in node.keywords:
                    self._walk_expr(kw.value)
                if self._call_may_await(node):
                    self._suspend(node.lineno)
            return
        key = self._key_of(node)
        if key is not None and isinstance(getattr(node, "ctx", ast.Load()), ast.Load):
            self._read(key, node.lineno)
            return
        for child in ast.iter_child_nodes(node):
            self._walk_expr(child)

    def _call_may_await(self, call: ast.Call) -> bool:
        """Interprocedural summary lookup: does this bare call suspend?"""
        for callee in self.tables.resolve_ref(
            self.module, call, call.func,
            non_self_methods=True,
            method_names_ok=lambda n: n not in _GENERIC_METHODS,
        ):
            if id(callee) in self.may_await:
                return True
        return False

    @staticmethod
    def _loaded_locals(node: ast.AST) -> set[str]:
        return {
            sub.id
            for sub in ast.walk(node)
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load)
        }

    def _read_keys(self, node: ast.AST) -> set[tuple[str, int]]:
        """Shared keys loaded anywhere in ``node`` (provenance for taint)."""
        out: set[tuple[str, int]] = set()
        for sub in ast.walk(node):
            if isinstance(sub, (*DEF_NODES, ast.Lambda)):
                continue
            key = self._key_of(sub)
            if key is not None and isinstance(getattr(sub, "ctx", None), ast.Load):
                # skip the pure method-lookup shape f(...) where sub is func
                parent = getattr(sub, "_graftlint_parent", None)
                if isinstance(parent, ast.Call) and parent.func is sub:
                    continue
                out.add((key, sub.lineno))
        return out

    # -- write shapes ---------------------------------------------------
    def _write_targets(self, stmt: ast.stmt) -> list[tuple[str, ast.AST]]:
        """(key, node) for every shared-state write this statement makes."""
        out: list[tuple[str, ast.AST]] = []

        def target_key(target: ast.AST) -> Optional[tuple[str, ast.AST]]:
            key = self._key_of(target)
            if key is not None:
                return key, target
            if isinstance(target, ast.Subscript):
                key = self._key_of(target.value)
                if key is not None:
                    return key, target
            if isinstance(target, (ast.Tuple, ast.List)):
                for elt in target.elts:
                    hit = target_key(elt)
                    if hit is not None:
                        out.append(hit)
            return None

        if isinstance(stmt, ast.Assign):
            for target in stmt.targets:
                hit = target_key(target)
                if hit is not None:
                    out.append(hit)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            hit = target_key(stmt.target)
            if hit is not None:
                out.append(hit)
        elif isinstance(stmt, ast.Delete):
            for target in stmt.targets:
                hit = target_key(target)
                if hit is not None:
                    out.append(hit)
        # in-place container mutation anywhere in the statement
        for sub in ast.walk(stmt):
            if isinstance(sub, (*DEF_NODES, ast.Lambda)):
                continue
            if (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr in _MUTATOR_METHODS
            ):
                key = self._key_of(sub.func.value)
                if key is not None:
                    out.append((key, sub))
        return out

    # -- statement walk -------------------------------------------------
    def walk(self, body: list[ast.stmt]) -> None:
        self._block(body, regions=[], under_lock=False)

    def _block(
        self, stmts: list[ast.stmt],
        regions: list[dict[str, int]], under_lock: bool,
    ) -> None:
        for stmt in stmts:
            self._stmt(stmt, regions, under_lock)

    def _snapshot(self):
        return (
            {k: _KeyState(v.read_line, v.stale_line)
             for k, v in self.state.items()},
            {k: set(v) for k, v in self.taint.items()},
        )

    def _merge(self, snapshots) -> None:
        """Conservative path join: stale on ANY arm wins; taint unions."""
        merged_state: dict[str, _KeyState] = {}
        merged_taint: dict[str, set] = {}
        for state, taint in snapshots:
            for key, st in state.items():
                cur = merged_state.get(key)
                if cur is None or (st.stale and not cur.stale):
                    merged_state[key] = _KeyState(st.read_line, st.stale_line)
            for name, origins in taint.items():
                merged_taint.setdefault(name, set()).update(origins)
        self.state = merged_state
        self.taint = merged_taint

    def _region_of(self, *exprs: ast.AST) -> dict[str, int]:
        region: dict[str, int] = {}
        for expr in exprs:
            if expr is None:
                continue
            for key, line in self._read_keys(expr):
                region[key] = line
        return region

    def _assign_taint(self, stmt: ast.stmt) -> None:
        """Propagate shared-read provenance into bound locals."""
        if isinstance(stmt, ast.Assign):
            value, targets = stmt.value, stmt.targets
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            value, targets = stmt.value, [stmt.target]
        elif isinstance(stmt, ast.AugAssign):
            value, targets = stmt.value, [stmt.target]
        else:
            return
        origins = set(self._read_keys(value))
        for name in self._loaded_locals(value):
            origins |= self.taint.get(name, set())
        for target in targets:
            for sub in ast.walk(target):
                if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Store):
                    if isinstance(stmt, ast.AugAssign):
                        self.taint.setdefault(sub.id, set()).update(origins)
                    elif origins:
                        self.taint[sub.id] = set(origins)
                    else:
                        self.taint.pop(sub.id, None)

    def _loop_taint(self, target: ast.AST, iter_expr: ast.AST) -> None:
        origins = set(self._read_keys(iter_expr))
        for name in self._loaded_locals(iter_expr):
            origins |= self.taint.get(name, set())
        if not origins:
            return
        for sub in ast.walk(target):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Store):
                self.taint[sub.id] = set(origins)

    def _stmt(
        self, stmt: ast.stmt,
        regions: list[dict[str, int]], under_lock: bool,
    ) -> None:
        if isinstance(stmt, (*DEF_NODES, ast.ClassDef)):
            return  # nested scope: runs when called, its own analysis unit
        if isinstance(stmt, ast.If):
            self._walk_expr(stmt.test)
            region = self._region_of(stmt.test)
            before = self._snapshot()
            self._block(stmt.body, regions + [region], under_lock)
            arm_a = self._snapshot()
            self.state, self.taint = before
            self._block(stmt.orelse, regions + [region], under_lock)
            arm_b = self._snapshot()
            self._merge([arm_a, arm_b])
            return
        if isinstance(stmt, ast.While):
            self._walk_expr(stmt.test)
            region = self._region_of(stmt.test)
            before = self._snapshot()
            self._block(stmt.body, regions + [region], under_lock)
            body_exit = self._snapshot()
            self._block(stmt.orelse, regions, under_lock)
            self._merge([before, body_exit, self._snapshot()])
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._walk_expr(stmt.iter)
            region = self._region_of(stmt.iter)
            self._loop_taint(stmt.target, stmt.iter)
            if isinstance(stmt, ast.AsyncFor):
                # each iteration step suspends BEFORE the body runs
                self._suspend(stmt.lineno)
            before = self._snapshot()
            self._block(stmt.body, regions + [region], under_lock)
            body_exit = self._snapshot()
            self._block(stmt.orelse, regions, under_lock)
            self._merge([before, body_exit, self._snapshot()])
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            locked = under_lock
            for item in stmt.items:
                expr = item.context_expr
                base = expr.func if isinstance(expr, ast.Call) else expr
                rendered = (
                    f"self.{_self_attr(base)}" if _self_attr(base) else
                    base.id if isinstance(base, ast.Name) else ""
                )
                if rendered in self.locks:
                    locked = True
                else:
                    self._walk_expr(expr)
            if isinstance(stmt, ast.AsyncWith) and not locked:
                self._suspend(stmt.lineno)
            self._block(stmt.body, regions, locked)
            return
        if isinstance(stmt, ast.Try):
            entry = self._snapshot()
            self._block(stmt.body, regions, under_lock)
            arms = [self._snapshot()]
            for handler in stmt.handlers:
                self.state, self.taint = (
                    {k: _KeyState(v.read_line, v.stale_line)
                     for k, v in entry[0].items()},
                    {k: set(v) for k, v in entry[1].items()},
                )
                self._block(handler.body, regions, under_lock)
                arms.append(self._snapshot())
            self._merge(arms)
            self._block(stmt.orelse, regions, under_lock)
            self._block(stmt.finalbody, regions, under_lock)
            return
        # ---- simple statement ----
        writes = self._write_targets(stmt)
        write_nodes = {id(node) for _k, node in writes}
        # evaluation order: the value/expression side first (reads refresh,
        # awaits stale), then the write check, then taint/store effects
        if isinstance(stmt, ast.Assign):
            self._walk_expr(stmt.value)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            if getattr(stmt, "value", None) is not None:
                self._walk_expr(stmt.value)
            if isinstance(stmt, ast.AugAssign):
                key = self._key_of(stmt.target)
                if key is not None:
                    # in-place RMW re-reads at the write: fresh by definition
                    self._read(key, stmt.lineno)
        elif isinstance(stmt, ast.Expr):
            # mutator-call receivers are the write itself, not a re-read:
            # walk arguments only for the mutating calls
            self._walk_expr_skipping_writes(stmt.value, write_nodes)
        elif isinstance(stmt, (ast.Return, ast.Raise, ast.Assert)):
            for child in ast.iter_child_nodes(stmt):
                self._walk_expr(child)
        else:
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self._walk_expr(child)
        stmt_locals = self._loaded_locals(stmt)
        for key, node in writes:
            self._write(key, node, stmt_locals, regions, under_lock)
        self._assign_taint(stmt)
        # a plain rebind of self.x makes the location's current value this
        # coroutine's own: later UNRELATED writes are not check-then-act,
        # but stale taint still flags derived writes (no read-state reset)

    def _walk_expr_skipping_writes(self, node: ast.AST, write_nodes: set[int]) -> None:
        if node is None or isinstance(node, (*DEF_NODES, ast.Lambda)):
            return
        if id(node) in write_nodes and isinstance(node, ast.Call):
            for arg in node.args:
                self._walk_expr(arg)
            for kw in node.keywords:
                self._walk_expr(kw.value)
            return
        if isinstance(node, (ast.Await, ast.Call)):
            self._walk_expr(node)
            return
        for child in ast.iter_child_nodes(node):
            self._walk_expr_skipping_writes(child, write_nodes)


class AwaitAtomicityRule(Rule):
    id = "GL011"
    name = "await-atomicity"
    description = (
        "a read of shared mutable state (self.* / module global) must not "
        "feed a later write across a suspension point (await, async for/"
        "with, may-await call) without a held lock or a re-read after the "
        "await — asyncio check-then-act is only atomic between awaits"
    )
    scope = (
        r"operator_tpu/operator/.*\.py$",
        r"operator_tpu/router/.*\.py$",
        r"operator_tpu/serving/.*\.py$",
        r"operator_tpu/obs/.*\.py$",
        # fleet KV fabric (ISSUE 19): the fetch client interleaves index
        # reads with awaited transport calls — stale-read check-then-act
        # here silently adopts pages a peer already dropped
        r"operator_tpu/fabric/.*\.py$",
    )

    def check(self, ctx: AnalysisContext) -> list[Finding]:
        modules = [m for m in ctx.in_scope(self.scope) if m.tree is not None]
        if not modules:
            return []
        tables = ctx.symbol_tables(modules)
        may_await = self._may_await_summaries(tables)
        findings: list[Finding] = []
        for module in modules:
            globals_ = _module_mutable_globals(module)
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.AsyncFunctionDef):
                    continue
                locks = _lock_names(module, _owner_class(node))
                walker = _FnWalker(
                    self, module, node, globals_, locks, may_await, tables
                )
                walker.walk(node.body)
                findings.extend(walker.findings.values())
        return findings

    # -- interprocedural suspension summaries ---------------------------
    def _may_await_summaries(self, tables: SymbolTables) -> set[int]:
        """Def node ids that may suspend the calling coroutine: async defs
        and anything that (transitively) calls one — the same resolution
        discipline as GL006's async-reachability, inverted into a
        may-await fixpoint."""
        from ..callgraph import iter_scope

        may_await: set[int] = set()
        calls: dict[int, list[ast.AST]] = {}
        defs: list[ast.AST] = []
        for module in tables.modules:
            for node in ast.walk(module.tree):
                if not isinstance(node, DEF_NODES):
                    continue
                defs.append(node)
                if isinstance(node, ast.AsyncFunctionDef):
                    may_await.add(id(node))
                callees: list[ast.AST] = []
                for stmt in node.body:
                    for sub in iter_scope(stmt):
                        if not isinstance(sub, ast.Call):
                            continue
                        chain = attr_chain(sub.func)
                        if chain and chain[-1] in _DETACH_CALLS:
                            continue
                        callees.extend(tables.resolve_ref(
                            module, sub, sub.func,
                            non_self_methods=True,
                            method_names_ok=lambda n: n not in _GENERIC_METHODS,
                        ))
                calls[id(node)] = callees
        changed = True
        while changed:
            changed = False
            for node in defs:
                if id(node) in may_await:
                    continue
                if any(id(c) in may_await for c in calls.get(id(node), ())):
                    may_await.add(id(node))
                    changed = True
        return may_await
