"""GL006: no blocking calls reachable from the event loop.

The control plane (``operator/``), the data-plane router (``router/``),
the observability surface (``obs/``) and the serving HTTP front
(``serving/httpserver.py``) are single-event-loop asyncio programs: one
synchronous file write, ``time.sleep`` or subprocess wait on the loop
stalls every lease renewal, health probe and streaming response at once
— the PR 6 failure mode (journal IO on the dispatch path) this rule
turns into a lint finding.

Mechanics — an interprocedural async-reachability walk on the shared
callgraph tables (``analysis/callgraph.py``, the same resolution
GL001/GL002's jit walk uses):

1. Seed: every ``async def`` in scope (handlers are registered
   dynamically, so an un-called async def still counts).
2. Propagate: direct calls resolve through module functions, ``from x
   import y`` imports, ``self.method`` (class-agnostic, as in
   jitgraph), and ``<recv>.method`` for method names that are not
   generic container-protocol names.  Function REFERENCES handed to
   ``asyncio.to_thread`` / ``run_in_executor`` / ``Thread(target=...)``
   / ``executor.submit`` are not calls on the loop and are never
   walked — that is the sanctioned escape hatch for blocking work.
3. Report: in every reachable function, flag ``time.sleep``, sync
   ``subprocess`` / ``os.system``, sync file IO (``open``,
   ``Path.read_text``/``write_text``, ``os.replace``/``rename``/
   ``fsync``), ``Future.result()`` (unless the receiver is proven done
   in an enclosing ``if x.done()``) — and sync :class:`Journal` traffic:
   ``append``/``compact``/``load``/``open`` on a journal constructed
   without ``async_writes=True``, plus ``append(..., wait=True)`` on ANY
   journal (a durable append blocks by definition; the claim ledger's
   durable-before-analysis write is the deliberate, pragma'd exception).
"""

from __future__ import annotations

import ast
from typing import Optional

from ..callgraph import DEF_NODES, SymbolTables, attr_chain, iter_scope
from ..core import AnalysisContext, Finding, ModuleSource, Rule

#: method names too generic to resolve across classes on a non-``self``
#: receiver (dict/list/queue/file protocol + ubiquitous helper names) —
#: ``self.method`` dispatch is unaffected
_GENERIC_METHODS = {
    "append", "add", "acquire", "cancel", "clear", "close", "copy",
    "count", "discard", "done", "extend", "flush", "get", "index",
    "insert", "items", "join", "keys", "load", "open", "parse", "pop",
    "popleft", "put", "read", "record", "release", "remove", "result",
    "run", "send", "set", "sort", "start", "submit", "to_dict",
    "update", "values", "wait", "write",
}

#: executor-style wrappers: a function REFERENCE in their arguments runs
#: off the loop, so it must not seed reachability
_OFFLOAD_CALLS = {"to_thread", "run_in_executor", "submit", "Thread",
                  "call_soon_threadsafe", "run_sync"}

_SYNC_SUBPROCESS = {"run", "call", "check_call", "check_output", "Popen"}
_SYNC_PATH_IO = {"read_text", "write_text", "read_bytes", "write_bytes"}
_SYNC_OS_IO = {"replace", "rename", "remove", "fsync", "system", "popen"}
_SYNC_SHUTIL = {"copy", "copy2", "copyfile", "copytree", "move", "rmtree"}
#: journal methods that perform IO on the calling thread in sync mode
_JOURNAL_SYNC_IO = {"append", "compact", "load", "open"}


def _truthy_kw(call: ast.Call, name: str) -> Optional[bool]:
    """True/False when ``name=`` is a boolean constant, None otherwise."""
    for kw in call.keywords:
        if kw.arg == name and isinstance(kw.value, ast.Constant):
            return bool(kw.value.value)
    return None


def _journal_attrs(module: ModuleSource) -> dict[int, dict[str, bool]]:
    """Per-class journal attributes: ClassDef id -> {attr: async_writes}.

    Detected from ``self.<attr> = Journal(...)`` (possibly inside a
    conditional expression).  ``async_writes`` defaults False, matching
    the Journal constructor."""
    out: dict[int, dict[str, bool]] = {}
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        attrs: dict[str, bool] = {}
        for child in ast.walk(node):
            if not isinstance(child, ast.Assign) or len(child.targets) != 1:
                continue
            target = child.targets[0]
            if not (
                isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"
            ):
                continue
            for sub in ast.walk(child.value):
                if (
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Name)
                    and sub.func.id == "Journal"
                ):
                    attrs[target.attr] = _truthy_kw(sub, "async_writes") is True
        if attrs:
            out[id(node)] = attrs
    return out


def _owner_class(node: ast.AST) -> Optional[ast.ClassDef]:
    parent = getattr(node, "_graftlint_parent", None)
    while parent is not None:
        if isinstance(parent, ast.ClassDef):
            return parent
        if isinstance(parent, ast.Module):
            return None
        parent = getattr(parent, "_graftlint_parent", None)
    return None


def _done_guarded(call: ast.Call) -> bool:
    """Is this ``x.result()`` lexically inside an ``if`` whose test calls
    ``x.done()`` on the same receiver?  A done future's result() does not
    block — the streaming peek path relies on exactly this shape."""
    receiver = ast.unparse(call.func.value)
    node: Optional[ast.AST] = call
    while node is not None:
        if isinstance(node, ast.If):
            for sub in ast.walk(node.test):
                if (
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr == "done"
                    and ast.unparse(sub.func.value) == receiver
                ):
                    return True
        node = getattr(node, "_graftlint_parent", None)
    return False


class EventLoopBlockingRule(Rule):
    id = "GL006"
    name = "event-loop-blocking"
    description = (
        "no blocking calls (sync file IO, time.sleep, subprocess, "
        "Future.result(), sync Journal appends) reachable from async "
        "def bodies in operator/, router/, obs/, serving/httpserver.py "
        "— offload via asyncio.to_thread / run_in_executor, or use "
        "Journal(async_writes=True)"
    )
    scope = (
        r"operator_tpu/operator/.*\.py$",
        r"operator_tpu/router/.*\.py$",
        r"operator_tpu/obs/.*\.py$",
        r"operator_tpu/serving/httpserver\.py$",
    )

    def check(self, ctx: AnalysisContext) -> list[Finding]:
        modules = [m for m in ctx.in_scope(self.scope) if m.tree is not None]
        tables = SymbolTables(modules)
        journal_by_class = {}
        for module in modules:
            journal_by_class.update(_journal_attrs(module))

        # -- async reachability -----------------------------------------
        reachable: dict[int, str] = {}  # def id -> origin async qualname
        worklist: list[ast.AST] = []
        for module in modules:
            for node in ast.walk(module.tree):
                if isinstance(node, ast.AsyncFunctionDef):
                    reachable[id(node)] = module.symbol_at(node)
                    worklist.append(node)
        while worklist:
            fn = worklist.pop()
            module = tables.module_of[id(fn)]
            origin = reachable[id(fn)]
            for stmt in fn.body:
                for node in iter_scope(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    chain = attr_chain(node.func)
                    if chain and chain[-1] in _OFFLOAD_CALLS:
                        continue  # args run off-loop, refs are not calls
                    for callee in tables.resolve_ref(
                        module, node, node.func,
                        non_self_methods=True,
                        method_names_ok=lambda n: n not in _GENERIC_METHODS,
                    ):
                        if id(callee) not in reachable:
                            reachable[id(callee)] = origin
                            worklist.append(callee)

        # -- blocking-call scan over the reachable set ------------------
        findings: list[Finding] = []
        node_by_id = {}
        for module in modules:
            for node in ast.walk(module.tree):
                if isinstance(node, DEF_NODES):
                    node_by_id[id(node)] = (node, module)
        for fn_id, origin in reachable.items():
            fn, module = node_by_id[fn_id]
            cls = _owner_class(fn)
            journal_attrs = journal_by_class.get(id(cls), {}) if cls else {}
            for stmt in fn.body:
                for node in iter_scope(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    message = self._blocking_message(
                        module, node, journal_attrs
                    )
                    if message is not None:
                        findings.append(self.finding(
                            module, node,
                            f"{message} on the event loop (reachable from "
                            f"async `{origin}`) — offload via "
                            "asyncio.to_thread / run_in_executor, or use "
                            "Journal(async_writes=True)",
                        ))
        return findings

    def _blocking_message(
        self,
        module: ModuleSource,
        call: ast.Call,
        journal_attrs: dict[str, bool],
    ) -> Optional[str]:
        chain = attr_chain(call.func)
        if not chain:
            return None
        if chain[-2:] == ["time", "sleep"]:
            return "blocking `time.sleep(...)`"
        if chain[0] == "subprocess" and chain[-1] in _SYNC_SUBPROCESS:
            return f"sync `subprocess.{chain[-1]}(...)`"
        if len(chain) == 2 and chain[0] == "os" and chain[1] in _SYNC_OS_IO:
            return f"sync `os.{chain[1]}(...)`"
        if chain == ["open"]:
            return "sync `open(...)` file IO"
        if len(chain) >= 2 and chain[-1] in _SYNC_PATH_IO:
            return f"sync `.{chain[-1]}(...)` file IO"
        if chain[0] == "shutil" and chain[-1] in _SYNC_SHUTIL:
            return f"sync `shutil.{chain[-1]}(...)` file IO"
        # Future.result(): blocking unless proven done
        if (
            chain[-1] == "result"
            and isinstance(call.func, ast.Attribute)
            and not _done_guarded(call)
        ):
            return "blocking `.result()` on a future"
        # Journal traffic on self-owned journal attributes
        if (
            len(chain) == 3
            and chain[0] == "self"
            and chain[1] in journal_attrs
        ):
            is_async = journal_attrs[chain[1]]
            method = chain[2]
            if method == "append":
                for kw in call.keywords:
                    if kw.arg != "wait":
                        continue
                    if isinstance(kw.value, ast.Constant) and not kw.value.value:
                        break  # wait=False: plain enqueue
                    # literal True or a pass-through variable: the caller
                    # CAN block the loop until the fsync completes
                    return (
                        f"durable `self.{chain[1]}.append(..., "
                        f"wait={ast.unparse(kw.value)})` (blocks until "
                        "flushed even in writer-thread mode)"
                    )
            if not is_async and method in _JOURNAL_SYNC_IO:
                return (
                    f"sync-mode Journal IO `self.{chain[1]}.{method}(...)` "
                    "(constructed without async_writes=True)"
                )
        return None
