"""GL012 — chaos-seam coverage: every external call must be faultable.

The chaos harness (``utils/faultinject.py``) only exercises failure paths
that pass through a registered seam — a ``fault_plan.apply("site", ...)``
call on the code path.  An external call that no seam governs is a failure
mode no chaos test can inject, and a seam no test names is a failure mode
nobody rehearses.  Both decay silently: a new kube verb or HTTP hop lands
green because only its happy path runs in CI.

The rule proves two properties for the control plane's side-effecting
sites (the same site set GL003 budgets, via
:func:`~.gl003_deadline.external_call_label`):

(a) **seam-reachable** — from the site's enclosing function, following the
    shared callgraph (``analysis/callgraph.py``) in BOTH directions
    (callees: the seam lives inside the op implementation, e.g.
    ``FakeKubeApi`` applying ``kube.<op>`` before the verb; callers: the
    seam fires before descending into the helper that owns the raw
    socket, e.g. ``http.provider`` wrapping the urlopen closure), some
    function contains a ``fault_plan.apply`` whose site pattern therefore
    governs the call;
(b) **test-named** — every registered seam pattern is named by at least
    one string literal in ``tests/``, ``loadgen/``, or ``chaos/``, or by
    a game-day scenario file under ``tests/scenarios/*.json`` (f-string
    seam sites register as fnmatch globs — ``f"kube.{op}"`` is
    ``kube.*`` — and a test naming ``kube.patch_status`` matches it; the
    comparison runs both directions so a test's own glob ``kube.*`` also
    matches a literal seam).

Scenario files are first-class seam sources, and the compact is
two-way: a seam a scenario names counts as rehearsed, and a scenario
naming a seam NO ``fault_plan.apply`` registers is a lint error — the
conductor would queue an injection nothing ever consumes, and the
game day's ``pending_faults`` gate would blame the scenario at run
time instead of the diff that renamed the seam.  The same unknown-seam
check covers literal ``Injection("<seam>", ...)`` construction in
chaos/test python.

The full audit is emitted as a deterministic ``seam-coverage.json`` map
(``--seam-coverage FILE``; byte-identical across runs on an unchanged
tree) that CI publishes as an artifact — the seam registry's contract
surface, reviewable in PR diffs.
"""

from __future__ import annotations

import ast
import fnmatch
import json
import re
from typing import Optional

from ..callgraph import DEF_NODES, attr_chain, iter_scope
from ..core import AnalysisContext, Finding, ModuleSource, Rule
from .gl003_deadline import DeadlinePropagation, _is_api_handle, _KUBE_OPS

#: non-self method names the reachability walk may resolve
#: class-agnostically — kube verbs on api handles plus the two provider
#: protocol names; anything wider would alias container protocol methods
#: across the tree
_EDGE_METHOD_NAMES = set(_KUBE_OPS) | {"generate", "communicate"}

#: literals in tests that plausibly name a seam: dotted lowercase head,
#: fnmatch metacharacters allowed in the tail ("kube.*", "kube.watch.Pod")
_SITE_LITERAL_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[A-Za-z0-9_*?\[\]]+)+$")

#: modules whose ``fault_plan.apply`` calls register seams — the package
#: itself, minus the analysis tree (rule fixtures/doc examples are not
#: seams) and minus loadgen and chaos (chaos DRIVERS: their literals
#: count as test-side naming, their apply calls — if any — are not
#: registrations)
_REGISTRY_SCOPE = re.compile(
    r"operator_tpu/(?!analysis/|loadgen/|chaos/).*\.py$"
)

#: committed game-day scenario files — seam-naming sources the gameday
#: lane replays (``LOADGEN_SCENARIO=<file.json>``)
_SCENARIO_DIR = "tests/scenarios"

#: a scenario injection's seam key in the JSON text, matched on the raw
#: source so findings carry real line numbers (json.loads drops them)
_SEAM_KEY_RE = re.compile(r'"seam"\s*:\s*"([^"]*)"')


def seam_pattern(call: ast.Call) -> Optional[str]:
    """The site pattern a ``fault_plan.apply(<arg0>, ...)`` or
    ``fault_plan.apply_async(<arg0>, ...)`` call registers: a literal
    string verbatim, an f-string with every interpolation widened to
    ``*`` (``f"kube.watch.{kind}"`` -> ``kube.watch.*``).  None when the
    call is not an apply on a fault-plan receiver or the site argument
    is not statically resolvable."""
    chain = attr_chain(call.func)
    if (
        len(chain) < 2
        or chain[-1] not in ("apply", "apply_async")
        or chain[-2] != "fault_plan"
    ):
        return None
    if not call.args:
        return None
    arg = call.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    if isinstance(arg, ast.JoinedStr):
        parts = []
        for piece in arg.values:
            if isinstance(piece, ast.Constant):
                parts.append(str(piece.value))
            else:
                parts.append("*")
        return "".join(parts)
    return None


def _patterns_match(seam: str, literal: str) -> bool:
    """Does a test literal name a seam pattern?  Either side may be the
    glob (seam patterns come from f-strings, test rules use fnmatch)."""
    return (
        seam == literal
        or fnmatch.fnmatch(literal, seam)
        or fnmatch.fnmatch(seam, literal)
    )


class ChaosSeamCoverage(Rule):
    id = "GL012"
    name = "chaos-seam-coverage"
    description = (
        "every blocking external call must be reachable from a registered "
        "fault_plan seam (utils/faultinject.py), every registered seam "
        "must be named by a chaos/loadgen test or a tests/scenarios/*.json "
        "game-day file, and every seam a scenario names must exist — emits "
        "the seam-coverage.json audit map"
    )
    #: sites audited — exactly the deadline rule's control-plane scope;
    #: the seam registry and the callgraph walk span the whole package
    scope = DeadlinePropagation.scope

    def check(self, ctx: AnalysisContext) -> list[Finding]:
        # the registry and the callgraph need the WHOLE package even when
        # only a subset was collected (--changed-only): a changed call
        # site's seam usually lives in an unchanged module (kubeapi's
        # kube.* apply governs every api verb in the tree), so coverage
        # is audited against the full tree, findings reported only on
        # collected files
        package = self._package_modules(ctx)
        tables = ctx.symbol_tables(package)

        # -- seam registry: pattern -> [(module, call node)] ------------
        registry: dict[str, list[tuple[ModuleSource, ast.Call]]] = {}
        defs_with_seams: dict[int, set[str]] = {}
        for module in package:
            if not _REGISTRY_SCOPE.match(module.relpath):
                continue
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.Call):
                    continue
                pattern = seam_pattern(node)
                if pattern is None:
                    continue
                registry.setdefault(pattern, []).append((module, node))
                owner = self._enclosing_def(node)
                if owner is not None:
                    defs_with_seams.setdefault(id(owner), set()).add(pattern)

        # -- def-level call edges over the whole package ----------------
        forward, reverse = ctx.memo(
            ("gl012", "call_edges"), lambda: self._call_edges(package, tables)
        )

        # -- external-call sites (GL003's enumeration) ------------------
        gl003 = DeadlinePropagation()
        sites = []  # (module, call node, label, enclosing defs)
        site_scope = [
            m for m in package
            if any(re.match(p, m.relpath) for p in self.scope)
        ]
        for module in site_scope:
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.Call):
                    continue
                label = gl003._external_call(node)
                if label is None:
                    continue
                sites.append((module, node, label, self._enclosing_defs(node)))

        # -- (a) seam reachability per site -----------------------------
        findings: list[Finding] = []
        site_rows = []
        for module, node, label, owners in sites:
            governing: set[str] = set()
            # every lexically-enclosing def is on the site's path (a nested
            # closure only runs on its parent's path — the http.provider
            # seam in generate() governs the urlopen inside its to_thread
            # closure), so reachability starts from all of them
            for owner in owners:
                for visited in self._bfs(owner, forward) | self._bfs(owner, reverse):
                    governing |= defs_with_seams.get(visited, set())
            # findings only on COLLECTED files (a --changed-only run
            # audits the whole tree but reports on what you touched —
            # and pragma suppression needs the module in the run)
            if not governing and ctx.module(module.relpath) is not None:
                findings.append(
                    self.finding(
                        module, node,
                        f"external call {label} is reachable from no "
                        "registered fault seam: chaos tests cannot inject "
                        "its failure — add a fault_plan.apply(...) seam on "
                        "its call path (utils/faultinject.py)",
                    )
                )
            site_rows.append({
                "path": module.relpath,
                "line": node.lineno,
                "symbol": module.symbol_at(node),
                "call": label,
                "seams": sorted(governing),
            })

        # -- (b) test naming per registered seam ------------------------
        # scenario files and chaos-package literals count alongside
        # tests/ and loadgen/: a committed game-day scenario rehearses
        # every seam it injects
        literals = self._test_literals(ctx)
        scenarios, scenario_findings = self._scenario_seams(ctx)
        findings.extend(scenario_findings)
        for relpath, rows in scenarios.items():
            literals.setdefault(relpath, set()).update(
                seam for seam, _line, _name in rows
            )
        seam_rows = []
        for pattern in sorted(registry):
            naming = sorted(
                path for path, found in literals.items()
                if any(_patterns_match(pattern, lit) for lit in found)
            )
            where = sorted(
                (module.relpath, call.lineno, module.symbol_at(call))
                for module, call in registry[pattern]
            )
            collected = [
                (module, call) for module, call in registry[pattern]
                if ctx.module(module.relpath) is not None
            ]
            if not naming and collected:
                module, call = min(
                    collected,
                    key=lambda pair: (pair[0].relpath, pair[1].lineno),
                )
                findings.append(
                    self.finding(
                        module, call,
                        f"fault seam `{pattern}` is named by no chaos/"
                        "loadgen test: the failure it injects is never "
                        "rehearsed — add a plan.rule scenario naming it "
                        "under tests/",
                    )
                )
            seam_rows.append({
                "pattern": pattern,
                "registered_at": [f"{p}:{ln} [{sym}]" for p, ln, sym in where],
                "tests": naming,
            })

        # -- (c) unknown seams in scenarios -----------------------------
        # a scenario (JSON file or literal Injection(...) in chaos/test
        # python) naming a seam no fault_plan.apply registers is dead
        # chaos: the conductor queues a rule nothing consumes and the
        # run-time pending_faults gate fires long after the rename that
        # broke it
        known = sorted(registry)
        for relpath in sorted(scenarios):
            for seam, line, scenario_name in scenarios[relpath]:
                if any(_patterns_match(p, seam) for p in known):
                    continue
                findings.append(Finding(
                    rule=self.id,
                    path=relpath,
                    line=line,
                    message=(
                        f"scenario names unknown fault seam `{seam}`: no "
                        "fault_plan.apply registers it, so the game day "
                        "queues an injection nothing can fire — fix the "
                        "seam name or register the seam "
                        "(utils/faultinject.py)"
                    ),
                    symbol=scenario_name,
                ))
        for module, node, seam in self._injection_literals(ctx, package):
            if any(_patterns_match(p, seam) for p in known):
                continue
            if ctx.module(module.relpath) is None:
                continue
            findings.append(
                self.finding(
                    module, node,
                    f"Injection names unknown fault seam `{seam}`: no "
                    "fault_plan.apply registers it, so the game day "
                    "queues an injection nothing can fire — fix the "
                    "seam name or register the seam "
                    "(utils/faultinject.py)",
                )
            )

        # stable artifact for --seam-coverage / CI (plain assignment: no
        # other rule touches this key, and dict stores are atomic)
        ctx.caches["seam_coverage"] = {
            "schema": 1,
            "seams": seam_rows,
            "external_call_sites": sorted(
                site_rows, key=lambda r: (r["path"], r["line"])
            ),
            "scenario_files": {
                relpath: sorted({seam for seam, _l, _n in rows})
                for relpath, rows in sorted(scenarios.items())
            },
            "uncovered_sites": sum(1 for r in site_rows if not r["seams"]),
            "unnamed_seams": sum(1 for r in seam_rows if not r["tests"]),
        }
        return findings

    # -- module enumeration ---------------------------------------------
    @staticmethod
    def _package_modules(ctx: AnalysisContext) -> list[ModuleSource]:
        """Every parsed module under ``operator_tpu/`` (excluding the
        analysis tree's own fixtures is the registry's job, not this
        one's), sourced from the filesystem so partial runs still see
        the whole package; per-file parses memoize on the context."""
        out = []
        base = ctx.root / "operator_tpu"
        if not base.is_dir():
            # fixture trees (tests) root the package elsewhere — fall
            # back to whatever was collected
            return [
                m for m in ctx.modules
                if m.relpath.startswith("operator_tpu/")
                and m.tree is not None
            ]
        for path in sorted(base.rglob("*.py")):
            relpath = path.relative_to(ctx.root).as_posix()
            if "__pycache__" in relpath:
                continue
            module = ctx.aux_module(relpath)
            if module is not None and module.tree is not None:
                out.append(module)
        return out

    # -- callgraph ------------------------------------------------------
    @staticmethod
    def _enclosing_def(node: ast.AST) -> Optional[ast.AST]:
        current = getattr(node, "_graftlint_parent", None)
        while current is not None:
            if isinstance(current, DEF_NODES):
                return current
            current = getattr(current, "_graftlint_parent", None)
        return None

    @staticmethod
    def _enclosing_defs(node: ast.AST) -> list[ast.AST]:
        """Every def lexically enclosing ``node``, innermost first."""
        out = []
        current = getattr(node, "_graftlint_parent", None)
        while current is not None:
            if isinstance(current, DEF_NODES):
                out.append(current)
            current = getattr(current, "_graftlint_parent", None)
        return out

    def _call_edges(self, package, tables):
        """Def-id -> called def-ids (forward) and the reverse map, built
        once per run (shared through the context memo).  Non-self method
        edges are restricted to api-handle kube verbs and the provider
        protocol names so generic ``get``/``list`` receivers do not alias
        the tree."""
        forward: dict[int, set[int]] = {}
        reverse: dict[int, set[int]] = {}
        for module in package:
            for owner in ast.walk(module.tree):
                if not isinstance(owner, DEF_NODES):
                    continue
                out = forward.setdefault(id(owner), set())
                for stmt in owner.body:
                    for node in iter_scope(stmt):
                        if not isinstance(node, ast.Call):
                            continue
                        target = node.func
                        allow_non_self = isinstance(
                            target, ast.Attribute
                        ) and (
                            _is_api_handle(target.value)
                            or target.attr in ("generate", "communicate")
                        )
                        for callee in tables.resolve_ref(
                            module, node, target,
                            non_self_methods=allow_non_self,
                            method_names_ok=lambda name: (
                                name in _EDGE_METHOD_NAMES
                            ),
                        ):
                            out.add(id(callee))
                            reverse.setdefault(id(callee), set()).add(id(owner))
                        # higher-order references: a function PASSED to a
                        # call (to_thread(call), run_in_executor(None, fn),
                        # dispatch(send=send)) may be called on the passing
                        # def's path — the http.provider seam in send()
                        # governs the urlopen inside the call() closure it
                        # ships to the worker thread
                        for arg in (
                            *node.args,
                            *(kw.value for kw in node.keywords),
                        ):
                            if not isinstance(arg, (ast.Name, ast.Attribute)):
                                continue
                            for callee in tables.resolve_ref(
                                module, node, arg,
                            ):
                                out.add(id(callee))
                                reverse.setdefault(
                                    id(callee), set()
                                ).add(id(owner))
        return forward, reverse

    @staticmethod
    def _bfs(start: ast.AST, edges: dict[int, set[int]]) -> set[int]:
        seen = {id(start)}
        frontier = [id(start)]
        while frontier:
            current = frontier.pop()
            for nxt in edges.get(current, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append(nxt)
        return seen

    # -- test-side naming -----------------------------------------------
    def _test_literals(self, ctx: AnalysisContext) -> dict[str, set[str]]:
        """Repo-relative test/loadgen path -> site-shaped string literals.
        Files are enumerated from the filesystem (not the collected set)
        so a ``--changed-only`` run still audits against the whole test
        tree; parses are memoized on the context."""
        out: dict[str, set[str]] = {}
        roots = ("tests", "operator_tpu/loadgen", "operator_tpu/chaos")
        for rel_root in roots:
            base = ctx.root / rel_root
            if not base.is_dir():
                continue
            for path in sorted(base.rglob("*.py")):
                relpath = path.relative_to(ctx.root).as_posix()
                module = ctx.aux_module(relpath)
                if module is None or module.tree is None:
                    continue
                found = {
                    node.value
                    for node in ast.walk(module.tree)
                    if isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and _SITE_LITERAL_RE.match(node.value)
                }
                if found:
                    out[relpath] = found
        return out

    # -- scenario files --------------------------------------------------
    def _scenario_seams(
        self, ctx: AnalysisContext
    ) -> "tuple[dict[str, list[tuple[str, int, str]]], list[Finding]]":
        """Repo-relative scenario path -> [(seam, line, scenario name)]
        for every ``tests/scenarios/*.json``, plus findings for files
        that do not parse (a committed repro the gameday lane cannot
        replay is itself a defect).  Seams and lines come from the raw
        text (``json.loads`` drops positions); the parse is only the
        well-formedness gate."""
        out: dict[str, list[tuple[str, int, str]]] = {}
        findings: list[Finding] = []
        base = ctx.root / _SCENARIO_DIR
        if not base.is_dir():
            return out, findings
        for path in sorted(base.glob("*.json")):
            relpath = path.relative_to(ctx.root).as_posix()
            try:
                text = path.read_text(encoding="utf-8")
                data = json.loads(text)
            except (OSError, ValueError) as exc:
                findings.append(Finding(
                    rule=self.id,
                    path=relpath,
                    line=1,
                    message=(
                        "scenario file is not valid JSON — the gameday "
                        f"lane cannot replay it ({exc})"
                    ),
                    symbol=path.stem,
                ))
                continue
            name = str(data.get("name", path.stem)) if isinstance(
                data, dict
            ) else path.stem
            rows = []
            for match in _SEAM_KEY_RE.finditer(text):
                line = text.count("\n", 0, match.start()) + 1
                rows.append((match.group(1), line, name))
            out[relpath] = rows
        return out, findings

    def _injection_literals(
        self, ctx: AnalysisContext, package: "list[ModuleSource]"
    ) -> "list[tuple[ModuleSource, ast.Call, str]]":
        """Literal first arguments of ``Injection(...)`` constructions in
        the chaos package and the test tree — python-side scenario
        definitions, held to the same known-seam bar as JSON files."""
        modules: dict[str, ModuleSource] = {
            m.relpath: m for m in package
            if m.relpath.startswith("operator_tpu/chaos/")
        }
        base = ctx.root / "tests"
        if base.is_dir():
            for path in sorted(base.rglob("*.py")):
                relpath = path.relative_to(ctx.root).as_posix()
                module = ctx.aux_module(relpath)
                if module is not None and module.tree is not None:
                    modules.setdefault(relpath, module)
        out = []
        for relpath in sorted(modules):
            module = modules[relpath]
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.Call):
                    continue
                chain = attr_chain(node.func)
                if not chain or chain[-1] != "Injection":
                    continue
                arg: Optional[ast.expr] = node.args[0] if node.args else None
                if arg is None:
                    for kw in node.keywords:
                        if kw.arg == "seam":
                            arg = kw.value
                if isinstance(arg, ast.Constant) and isinstance(
                    arg.value, str
                ):
                    out.append((module, node, arg.value))
        return out
