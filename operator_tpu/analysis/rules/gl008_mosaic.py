"""GL008: Pallas kernel bodies must stay Mosaic-lowerable.

A ``pallas_call`` kernel compiles through Mosaic, which supports a
narrower op set than XLA: ``argmax``/``argmin``/``sort``/``top_k`` and
friends have no TPU lowering inside a kernel, 1-D ``lax.iota`` is
rejected (Mosaic needs >=2-D; use ``lax.broadcasted_iota``), and
integer reductions hit the "Only float32 and bfloat16 reductions
supported" wall.  Today these fail at compile time at best — on an
interpreter-mode CI (``interpret=True``) they pass silently and only
explode on real hardware.  This rule moves the failure to lint time.

Kernel discovery handles the repo's binding idiom, which the jit
graph's entry detection does not see through::

    kernel = functools.partial(_best_window_kernel, num_windows=n, ...)
    out = pl.pallas_call(kernel, grid=..., ...)

i.e. the first ``pallas_call`` argument may be the kernel def directly,
an inline ``partial(...)``, or a local Name bound to either — resolved
by scanning the enclosing function's assignments.  The closure then
expands through calls resolvable on the shared
:class:`~..callgraph.SymbolTables` and through decorated nested defs
(``@pl.when``).

The sanctioned replacement idiom — manual argmax via
``broadcasted_iota`` + ``jnp.where`` + float min/max, as in
``ops/similarity.py`` — contains none of the banned calls and stays
quiet by construction.  Integer-reduction detection is a small local
dtype inference (int iff provably int: iota results, ``.astype(int)``,
int-dtype creators, int-propagating arithmetic); unknown dtypes are NOT
flagged — the rule prefers silence to crying wolf on f32 code.
"""

from __future__ import annotations

import ast
from typing import Optional

from ..callgraph import DEF_NODES, SymbolTables, attr_chain, iter_scope
from ..core import AnalysisContext, Finding, ModuleSource, Rule

#: ops with no Mosaic lowering inside a kernel, rooted at jnp/jax/lax
_UNLOWERABLE = {
    "argmax", "argmin", "argsort", "sort", "top_k", "sort_key_val",
    "approx_max_k", "approx_min_k", "nonzero", "unique", "median",
    "searchsorted",
}
_ARRAY_ROOTS = {"jnp", "jax", "lax", "np", "numpy"}
#: reductions that only lower for f32/bf16 on TPU
_REDUCTIONS = {"sum", "prod", "max", "min", "cumsum", "cumprod"}
#: dtype spellings that mean "integer"
_INT_DTYPES = {
    "int8", "int16", "int32", "int64", "uint8", "uint16", "uint32",
    "uint64", "int_", "intp", "integer",
}


def _is_pallas_call(func: ast.AST) -> bool:
    if isinstance(func, ast.Name):
        return func.id == "pallas_call"
    return isinstance(func, ast.Attribute) and func.attr == "pallas_call"


def _is_int_dtype_expr(expr: ast.AST) -> bool:
    """Does this expression spell an integer dtype (``jnp.int32``,
    ``"int32"``, ``np.dtype("int32")``)?"""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value.startswith(("int", "uint"))
    chain = attr_chain(expr)
    if chain and chain[-1] in _INT_DTYPES:
        return True
    if isinstance(expr, ast.Call):
        return any(_is_int_dtype_expr(a) for a in expr.args)
    return False


class _IntTyper:
    """Tiny flow-insensitive int-dtype inference over one kernel body."""

    def __init__(self, body: list) -> None:
        self.int_names: set[str] = set()
        # two passes: straight-line `idx = iota(...); s = idx + 1` chains
        for _ in range(2):
            for stmt in body:
                for node in iter_scope(stmt):
                    if isinstance(node, ast.Assign) and self.is_int(node.value):
                        for target in node.targets:
                            for leaf in ast.walk(target):
                                if isinstance(leaf, ast.Name):
                                    self.int_names.add(leaf.id)

    def is_int(self, expr: ast.AST) -> bool:
        if isinstance(expr, ast.Name):
            return expr.id in self.int_names
        if isinstance(expr, ast.Constant):
            return isinstance(expr.value, int) and not isinstance(
                expr.value, bool
            )
        if isinstance(expr, ast.Subscript):
            return self.is_int(expr.value)
        if isinstance(expr, ast.BinOp):
            return self.is_int(expr.left) and self.is_int(expr.right)
        if isinstance(expr, ast.UnaryOp):
            return self.is_int(expr.operand)
        if isinstance(expr, ast.Call):
            chain = attr_chain(expr.func)
            if not chain:
                return False
            if chain[-1] == "astype" and expr.args:
                return _is_int_dtype_expr(expr.args[0])
            if chain[-1] in ("iota", "broadcasted_iota"):
                # iota's dtype is its FIRST argument in jax; int by default
                dtype = expr.args[0] if expr.args else None
                for kw in expr.keywords:
                    if kw.arg == "dtype":
                        dtype = kw.value
                if dtype is None:
                    return True
                return _is_int_dtype_expr(dtype)
            if chain[-1] in ("zeros", "ones", "full", "arange", "array"):
                for kw in expr.keywords:
                    if kw.arg == "dtype":
                        return _is_int_dtype_expr(kw.value)
                return chain[-1] == "arange"
            if chain[-1] == "where" and len(expr.args) == 3:
                return self.is_int(expr.args[1]) and self.is_int(expr.args[2])
            return False
        return False


class MosaicLowerabilityRule(Rule):
    id = "GL008"
    name = "mosaic-lowerability"
    description = (
        "pallas_call kernel bodies must avoid ops with no Mosaic/TPU "
        "lowering: argmax/argmin/sort/top_k (use the broadcasted_iota + "
        "where + float-min manual form), 1-D lax.iota (use "
        "broadcasted_iota), and integer reductions (reduce in f32, cast "
        "at the edge)"
    )
    scope = (
        r"operator_tpu/ops/.*\.py$",
        r"operator_tpu/serving/.*\.py$",
        r"operator_tpu/models/.*\.py$",
    )

    def check(self, ctx: AnalysisContext) -> list[Finding]:
        modules = [m for m in ctx.in_scope(self.scope) if m.tree is not None]
        tables = SymbolTables(modules)

        # -- kernel discovery: every pallas_call's first argument -------
        kernels: list[tuple[ast.AST, ModuleSource]] = []
        seen: set[int] = set()
        for module in tables.modules:
            for node in ast.walk(module.tree):
                if not (isinstance(node, ast.Call) and _is_pallas_call(node.func)):
                    continue
                target = node.args[0] if node.args else None
                if target is None:
                    continue
                for fn in self._kernel_defs(tables, module, node, target):
                    if id(fn) not in seen:
                        seen.add(id(fn))
                        kernels.append((fn, module))

        # -- closure: calls + decorated nested defs (@pl.when) ----------
        worklist = list(kernels)
        while worklist:
            fn, module = worklist.pop()
            owner = tables.module_of.get(id(fn), module)
            body = fn.body if isinstance(fn.body, list) else [ast.Expr(fn.body)]
            for stmt in body:
                for node in iter_scope(stmt):
                    callees: list[ast.AST] = []
                    if isinstance(node, DEF_NODES) and node.decorator_list:
                        callees = [node]
                    elif isinstance(node, ast.Call):
                        callees = tables.resolve_ref(owner, node, node.func)
                    for callee in callees:
                        if id(callee) not in seen:
                            seen.add(id(callee))
                            entry = (callee, tables.module_of.get(id(callee), owner))
                            kernels.append(entry)
                            worklist.append(entry)

        # -- scan the kernel closure for unlowerable ops ----------------
        findings: list[Finding] = []
        for fn, module in kernels:
            owner = tables.module_of.get(id(fn), module)
            qualname = owner.symbol_at(fn)
            body = fn.body if isinstance(fn.body, list) else [ast.Expr(fn.body)]
            typer = _IntTyper(body)
            for stmt in body:
                for node in iter_scope(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    message = self._unlowerable(node, typer)
                    if message is not None:
                        findings.append(self.finding(
                            owner, node,
                            f"{message} inside Pallas kernel "
                            f"`{qualname}` — no Mosaic/TPU lowering; "
                            "see docs/ANALYSIS.md (GL008)",
                        ))
        return findings

    def _kernel_defs(
        self,
        tables: SymbolTables,
        module: ModuleSource,
        site: ast.AST,
        target: ast.AST,
    ) -> list[ast.AST]:
        """Resolve a pallas_call's kernel argument: a def reference, an
        inline ``partial(...)``, or a local Name bound to either."""
        if isinstance(target, ast.Call):  # partial(kernel, ...)
            return (
                self._kernel_defs(tables, module, site, target.args[0])
                if target.args else []
            )
        direct = tables.resolve_ref(module, site, target)
        if direct:
            return direct
        if isinstance(target, ast.Name):
            # `kernel = functools.partial(_kernel, ...)` in an enclosing
            # function: find the binding assignment and unwrap it
            scope = getattr(site, "_graftlint_parent", None)
            while scope is not None:
                if isinstance(scope, DEF_NODES):
                    for stmt in scope.body:
                        for node in iter_scope(stmt):
                            if not isinstance(node, ast.Assign):
                                continue
                            if any(
                                isinstance(t, ast.Name) and t.id == target.id
                                for t in node.targets
                            ):
                                value = node.value
                                if isinstance(value, ast.Call):
                                    return self._kernel_defs(
                                        tables, module, node, value
                                    )
                                return tables.resolve_ref(module, node, value)
                scope = getattr(scope, "_graftlint_parent", None)
        return []

    def _unlowerable(
        self, call: ast.Call, typer: _IntTyper
    ) -> Optional[str]:
        chain = attr_chain(call.func)
        if not chain:
            return None
        leaf = chain[-1]
        rooted = chain[0] in _ARRAY_ROOTS and len(chain) >= 2
        if leaf in _UNLOWERABLE and rooted:
            return f"`{'.'.join(chain)}(...)`"
        if leaf == "iota" and rooted:
            # jax.lax.iota(dtype, size) is ALWAYS 1-D — the Mosaic-
            # rejected form; broadcasted_iota is the lowerable spelling
            return (
                "1-D `lax.iota(...)` (use `lax.broadcasted_iota` with a "
                ">=2-D shape)"
            )
        if leaf in _REDUCTIONS:
            int_typed = False
            for kw in call.keywords:
                if kw.arg == "dtype" and _is_int_dtype_expr(kw.value):
                    int_typed = True
            if rooted and call.args and typer.is_int(call.args[0]):
                int_typed = True
            if (
                not rooted
                and isinstance(call.func, ast.Attribute)
                and typer.is_int(call.func.value)
            ):
                int_typed = True  # x.sum() where x is int-typed
            if int_typed:
                return (
                    f"integer reduction `{'.'.join(chain)}(...)` (TPU only "
                    "lowers f32/bf16 reductions — reduce in f32, cast at "
                    "the edge)"
                )
        return None
