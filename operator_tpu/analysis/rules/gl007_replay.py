"""GL007: replay-gated modules must not read ambient time or entropy.

The storm harness replays a recorded open-loop trace and asserts the
fleet reproduces the same admission/eviction/SLO decisions; the fault
injector replays failure schedules; resume replays journal suffixes.
One raw ``time.time()`` in a decision path or one unseeded
``random.random()`` silently forks the replay from the recording and
every downstream assertion becomes noise.

Convention this rule enforces (the "seam" convention, used throughout
``loadgen/`` and ``router/health.py``):

- decision clocks are injected: ``self._clock = clock or time.monotonic``
  stores a bare UNCALLED reference — that is the seam, and it is never
  flagged (the rule only matches ``Call`` nodes).  A direct
  ``time.time()`` / ``time.monotonic()`` CALL in scope is a finding.
- ``time.perf_counter()`` is measurement-only (histograms, step-clock
  timings) and never drives a decision — always allowed.
- randomness must be a seeded generator threaded through the seam:
  ``random.Random(seed)`` / ``np.random.default_rng(seed)`` are fine;
  module-level ``random.*`` functions, zero-arg ``random.Random()``,
  ``SystemRandom`` and legacy ``np.random.*`` draws are findings.
"""

from __future__ import annotations

import ast
from typing import Optional

from ..callgraph import attr_chain
from ..core import AnalysisContext, Finding, Rule

#: time.* / datetime.* reads that fork a replay when called directly
_WALL_CLOCK = {"time", "monotonic", "time_ns", "monotonic_ns"}
_DATETIME_NOW = {"now", "utcnow", "today"}
#: module-level random.* draws (random.Random(seed) is the sanctioned form)
_RANDOM_MODULE_FNS = {
    "random", "randint", "randrange", "uniform", "choice", "choices",
    "shuffle", "sample", "gauss", "normalvariate", "expovariate",
    "betavariate", "triangular", "getrandbits", "randbytes",
}
#: np.random legacy draws; the generator constructors below are exempt
#: when given an explicit seed argument
_NP_SEEDED_CTORS = {"default_rng", "SeedSequence", "PCG64", "Philox"}


class ReplayDeterminismRule(Rule):
    id = "GL007"
    name = "replay-determinism"
    description = (
        "replay-gated modules (loadgen/, faultinject, sched planning, "
        "router dispatch, SLO ledger) must not call wall clocks "
        "(time.time/monotonic — inject a clock seam; perf_counter is "
        "measurement-only and allowed) or unseeded randomness "
        "(random.*, Random(), np.random.* — thread a seeded generator)"
    )
    scope = (
        r"operator_tpu/loadgen/.*\.py$",
        r"operator_tpu/utils/faultinject\.py$",
        r"operator_tpu/serving/sched/.*\.py$",
        r"operator_tpu/router/.*\.py$",
        r"operator_tpu/obs/sloledger\.py$",
        # serverless-fleet arc (PR 17): the autoscaler's decide() is pure
        # against an injected clock — a bare time.time()/random there
        # would fork chaos replays of scale decisions (discovery.py rides
        # the router/ glob above)
        r"operator_tpu/operator/autoscale\.py$",
    )

    def check(self, ctx: AnalysisContext) -> list[Finding]:
        findings: list[Finding] = []
        for module in ctx.in_scope(self.scope):
            if module.tree is None:
                continue
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.Call):
                    continue
                message = self._nondeterminism(node)
                if message is not None:
                    findings.append(self.finding(module, node, message))
        return findings

    def _nondeterminism(self, call: ast.Call) -> Optional[str]:
        chain = attr_chain(call.func)
        if len(chain) < 2:
            return None
        root, leaf = chain[0], chain[-1]
        if root == "time" and leaf in _WALL_CLOCK:
            return (
                f"direct wall-clock read `time.{leaf}()` in a replay-gated "
                "module — inject a clock seam (`clock or time.monotonic`, "
                "called via the seam) so replays can pin time; "
                "time.perf_counter() is allowed for measurement"
            )
        if root == "datetime" and leaf in _DATETIME_NOW:
            return (
                f"direct wall-clock read `datetime.{leaf}()` in a "
                "replay-gated module — derive timestamps from the injected "
                "clock seam"
            )
        if root == "random":
            if leaf in _RANDOM_MODULE_FNS:
                return (
                    f"unseeded module-level `random.{leaf}(...)` — draw "
                    "from a `random.Random(seed)` instance threaded "
                    "through the config/seam"
                )
            if leaf == "Random" and not call.args and not call.keywords:
                return (
                    "`random.Random()` without a seed — pass the replay "
                    "seed explicitly"
                )
            if leaf == "SystemRandom":
                return (
                    "`random.SystemRandom()` is OS entropy and can never "
                    "replay — use `random.Random(seed)`"
                )
        if len(chain) >= 3 and chain[-2] == "random" and root in {
            "np", "numpy",
        }:
            if leaf in _NP_SEEDED_CTORS and (call.args or call.keywords):
                return None
            return (
                f"legacy `{root}.random.{leaf}(...)` draws from global "
                "numpy state — use `np.random.default_rng(seed)` threaded "
                "through the seam"
            )
        return None
