"""GL001 — no host synchronisation inside the TPU hot path.

A ``.item()`` / ``np.asarray`` / ``jax.device_get`` / ``block_until_ready``
inside anything reachable from a ``jax.jit``/``pallas_call`` entry point
forces a device→host readback at trace time (or worse, per step): the
decode loop that is supposed to dispatch K steps per host round-trip
(serving/engine.py) silently serialises the TPU instead — the exact failure
mode the ragged/paged attention kernels exist to avoid.

Scope: the compute tree — ``ops/``, ``serving/``, ``models/``.  Host-side
orchestration in those files (admission, the step() token fetch — "the ONE
host sync per block") is fine: the rule only looks INSIDE the reachable
set computed by :mod:`..jitgraph`.

``float(x)`` / ``int(x)`` / ``bool(x)`` are flagged only when ``x`` is
*tainted* (derives from a traced value): on a tracer these raise
``ConcretizationTypeError`` at best and force a sync at worst, while
``float(len(xs))``-style host arithmetic stays legal.
"""

from __future__ import annotations

import ast

from ..core import AnalysisContext, Finding, Rule
from ..jitgraph import JitGraph, _func_root, iter_scope

#: numpy module aliases in this codebase
_NUMPY_ALIASES = {"np", "numpy", "onp"}
#: numpy calls that materialise (copy to host) an array
_NUMPY_MATERIALIZERS = {"asarray", "array", "ascontiguousarray", "copy", "save"}
#: method calls on any object that force a device->host readback
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
#: jax module-level sync functions
_JAX_SYNC_FUNCS = {"device_get", "block_until_ready"}


class HostSyncInHotPath(Rule):
    id = "GL001"
    name = "host-sync-in-hot-path"
    description = (
        "no .item()/tolist()/np.asarray/jax.device_get/block_until_ready — "
        "and no float()/int() on traced values — in functions reachable "
        "from jax.jit / pallas_call entry points"
    )
    scope = (
        r"operator_tpu/ops/.*\.py$",
        r"operator_tpu/serving/.*\.py$",
        r"operator_tpu/models/.*\.py$",
    )

    def check(self, ctx: AnalysisContext) -> list[Finding]:
        graph = JitGraph.for_modules(ctx, ctx.in_scope(self.scope))
        findings: list[Finding] = []
        for info in graph.reachable_functions():
            env = graph.local_taint(info)
            body = info.node.body if isinstance(info.node.body, list) else [
                ast.Expr(info.node.body)
            ]
            for stmt in body:
                for node in iter_scope(stmt):
                    # nested defs are their own reachable infos: iter_scope
                    # never descends into them, so no duplicate findings
                    if not isinstance(node, ast.Call):
                        continue
                    message = self._sync_message(graph, node, env, info.module)
                    if message is not None:
                        findings.append(
                            self.finding(
                                info.module, node,
                                f"{message} in jit/pallas hot path "
                                f"(reachable from a compiled entry point)",
                            )
                        )
        return findings

    def _sync_message(
        self, graph: JitGraph, call: ast.Call, env: set[str], module
    ) -> str | None:
        func = call.func
        if isinstance(func, ast.Attribute):
            root = _func_root(func)
            if func.attr in _SYNC_METHODS and root not in _NUMPY_ALIASES:
                return f"host sync: .{func.attr}()"
            if root == "jax" and func.attr in _JAX_SYNC_FUNCS:
                return f"host sync: jax.{func.attr}()"
            if root in _NUMPY_ALIASES and func.attr in _NUMPY_MATERIALIZERS:
                return f"host materialisation: {root}.{func.attr}()"
        elif isinstance(func, ast.Name):
            if func.id in _JAX_SYNC_FUNCS:
                return f"host sync: {func.id}()"
            if (
                func.id in ("float", "int", "bool")
                and call.args
                and graph.expr_tainted(call.args[0], env, module)
            ):
                return f"host sync: {func.id}() on a traced value"
        return None
