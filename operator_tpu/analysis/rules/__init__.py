"""Rule registry — one module per rule, imported here in catalogue order."""

from __future__ import annotations

from ..core import Rule
from .gl001_host_sync import HostSyncInHotPath
from .gl002_tracer import TracerUnsafeControlFlow
from .gl003_deadline import DeadlinePropagation
from .gl004_locks import LockDiscipline
from .gl005_drift import GeneratedArtifactDrift
from .gl006_eventloop import EventLoopBlockingRule
from .gl007_replay import ReplayDeterminismRule
from .gl008_mosaic import MosaicLowerabilityRule
from .gl009_release import ResourceReleaseRule
from .gl010_config import ConfigDriftRule
from .gl011_await_atomicity import AwaitAtomicityRule
from .gl012_seam_coverage import ChaosSeamCoverage
from .gl013_mesh_axes import MeshAxisConsistency

ALL_RULES: list[Rule] = [
    HostSyncInHotPath(),
    TracerUnsafeControlFlow(),
    DeadlinePropagation(),
    LockDiscipline(),
    GeneratedArtifactDrift(),
    EventLoopBlockingRule(),
    ReplayDeterminismRule(),
    MosaicLowerabilityRule(),
    ResourceReleaseRule(),
    ConfigDriftRule(),
    AwaitAtomicityRule(),
    ChaosSeamCoverage(),
    MeshAxisConsistency(),
]


def rules_by_id(ids: list[str] | None = None) -> list[Rule]:
    if not ids:
        return list(ALL_RULES)
    table = {rule.id: rule for rule in ALL_RULES}
    missing = [i for i in ids if i not in table]
    if missing:
        raise KeyError(f"unknown rule id(s): {', '.join(missing)}")
    return [table[i] for i in ids]
