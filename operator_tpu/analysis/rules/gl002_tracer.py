"""GL002 — tracer-unsafe Python control flow inside compiled bodies.

Inside a jitted/Pallas body, a Python ``if``/``while`` on a traced value
either raises ``ConcretizationTypeError`` or — when it "works" because the
value was concrete at trace time — silently bakes one branch into the
compiled program, which then serves WRONG results for other inputs.
``assert`` on a traced value is the same trap with a nicer spelling; Python
``for`` over a traced array unrolls the loop into the program (compile-time
explosion, recompile per length).

The taint model (``jitgraph``) keeps the legal idioms quiet: branching on
``static_argnames`` parameters, on shape/dtype metadata, on closure
configuration, and ``x is None`` pytree dispatch are all static at trace
time and never flagged.  The fix for a real finding is ``jnp.where`` /
``lax.cond`` / ``lax.while_loop`` / ``lax.fori_loop``.
"""

from __future__ import annotations

import ast

from ..core import AnalysisContext, Finding, Rule
from ..jitgraph import JitGraph, iter_scope


class TracerUnsafeControlFlow(Rule):
    id = "GL002"
    name = "tracer-unsafe-control-flow"
    description = (
        "no Python if/while/assert on traced values (and no Python "
        "iteration over traced arrays) inside jit/pallas bodies — use "
        "jnp.where / lax.cond / lax.while_loop"
    )
    scope = (
        r"operator_tpu/ops/.*\.py$",
        r"operator_tpu/serving/.*\.py$",
        r"operator_tpu/models/.*\.py$",
    )

    def check(self, ctx: AnalysisContext) -> list[Finding]:
        graph = JitGraph.for_modules(ctx, ctx.in_scope(self.scope))
        findings: list[Finding] = []
        for info in graph.reachable_functions():
            env = graph.local_taint(info)
            module = info.module
            vararg = getattr(info.node.args, "vararg", None)
            tuple_params = {vararg.arg} if vararg else set()
            body = info.node.body if isinstance(info.node.body, list) else [
                ast.Expr(info.node.body)  # jitted lambda: check its expression
            ]
            for stmt in body:
                for node in iter_scope(stmt):
                    message: str | None = None
                    if isinstance(node, ast.If) and graph.expr_tainted(
                        node.test, env, module
                    ):
                        message = (
                            "Python `if` on a traced value inside a compiled "
                            "body — use jnp.where / lax.cond"
                        )
                    elif isinstance(node, ast.While) and graph.expr_tainted(
                        node.test, env, module
                    ):
                        message = (
                            "Python `while` on a traced value inside a "
                            "compiled body — use lax.while_loop"
                        )
                    elif isinstance(node, ast.Assert) and graph.expr_tainted(
                        node.test, env, module
                    ):
                        message = (
                            "`assert` on a traced value inside a compiled "
                            "body — use checkify or a host-side precondition"
                        )
                    elif isinstance(node, ast.For) and self._iter_flaggable(
                        node.iter, tuple_params
                    ) and graph.expr_tainted(node.iter, env, module):
                        message = (
                            "Python iteration over a traced value unrolls "
                            "into the program — use lax.scan / lax.fori_loop"
                        )
                    elif isinstance(node, ast.IfExp) and graph.expr_tainted(
                        node.test, env, module
                    ):
                        message = (
                            "conditional expression on a traced value inside "
                            "a compiled body — use jnp.where"
                        )
                    if message is not None:
                        findings.append(self.finding(info.module, node, message))
        return findings

    @staticmethod
    def _iter_flaggable(iter_expr: ast.AST, tuple_params: set[str]) -> bool:
        """Iterating a *call result* (helpers returning host tuples) or a
        ``*args`` tuple of arrays is host iteration, not array unrolling —
        only direct traced values (names/attributes/subscripts) flag."""
        if isinstance(iter_expr, ast.Call):
            return False
        if isinstance(iter_expr, ast.Name) and iter_expr.id in tuple_params:
            return False
        return True
