"""GL010: config fields, deploy env rows and docs must round-trip.

``OperatorConfig.from_env`` maps every dataclass field to the env var
``FIELD.upper()`` (one reference-inherited exception:
``watch_namespaces`` -> ``PODMORTEM_WATCH_NAMESPACES``).  That mapping
is the operator's entire public configuration surface, and it drifts in
three directions, each of which has a distinct failure smell:

- a field with NO mention in README.md or docs/ is an invisible knob —
  operators discover it by reading source during an incident;
- a ``- name: X`` env row in a deploy manifest that no config field or
  ``os.environ`` read consumes is a silently-dead setting — the
  deployment LOOKS configured, the process never reads it (the classic
  renamed-field hazard);
- a README env-table row naming an env nothing reads documents a knob
  that does not exist.

The rule therefore cross-references four surfaces: config fields
(parsed from ``utils/config.py``), code-level ``os.environ``/
``os.getenv`` reads (regex scan, same technique as GL005's metric
scan), ``deploy/**/*.yaml`` env rows, and the README/docs text.
Pragmas cannot annotate YAML/Markdown, so deliberate exceptions go in
the committed baseline — which this repo keeps EMPTY, so there are
none.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from ..core import AnalysisContext, Finding, Rule

#: code-level env reads (string-literal keys only; from_env's computed
#: keys are covered by the field mapping itself)
_ENV_READ = re.compile(
    r"(?:os\.environ\.get|os\.environ\[|os\.getenv|environ\.get)"
    r"\s*\(?\s*[\"']([A-Z][A-Z0-9_]*)[\"']"
)
#: a k8s env row: `- name: UPPER_SNAKE` (ports/volumes/containers use
#: lowercase names and never match)
_YAML_ENV_ROW = re.compile(r"^\s*-\s*name:\s*([A-Z][A-Z0-9_]*)\s*$")
#: backticked env names in a README table row's first cell
_README_ROW = re.compile(r"^\|[^|]*\|")
_BACKTICKED_ENV = re.compile(r"`([A-Z][A-Z0-9_]*)`")

CONFIG_RELPATH = "operator_tpu/utils/config.py"


def _config_fields(tree: ast.Module) -> dict[str, tuple[str, int]]:
    """field name -> (env var, line) for every OperatorConfig field,
    mirroring from_env's mapping."""
    out: dict[str, tuple[str, int]] = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.ClassDef) and node.name == "OperatorConfig"):
            continue
        for stmt in node.body:
            if isinstance(stmt, ast.AnnAssign) and isinstance(
                stmt.target, ast.Name
            ):
                name = stmt.target.id
                env = (
                    "PODMORTEM_WATCH_NAMESPACES"
                    if name == "watch_namespaces"
                    else name.upper()
                )
                out[name] = (env, stmt.lineno)
    return out


def _code_read_envs(root: Path) -> set[str]:
    """Every env var name the code reads by string literal — the package
    plus the root-level entry points (bench.py) and scripts/, which read
    BENCH_* / CI knobs the README documents."""
    paths: list[Path] = sorted(root.glob("*.py"))
    for sub in ("operator_tpu", "scripts"):
        if (root / sub).is_dir():
            paths.extend(sorted((root / sub).rglob("*.py")))
    names: set[str] = set()
    for path in paths:
        text = path.read_text(encoding="utf-8", errors="replace")
        names.update(_ENV_READ.findall(text))
    return names


class ConfigDriftRule(Rule):
    id = "GL010"
    name = "config-env-doc-drift"
    description = (
        "every OperatorConfig field must round-trip: its env var "
        "documented under README/docs, every deploy-manifest env row "
        "consumed by a config field or os.environ read, every README "
        "env-table row backed by something that reads it"
    )
    scope = (
        r"operator_tpu/utils/config\.py$",
        r"deploy/.*\.yaml$",
        r"README\.md$",
    )

    def check(self, ctx: AnalysisContext) -> list[Finding]:
        config_module = ctx.module(CONFIG_RELPATH)
        if config_module is not None and config_module.tree is not None:
            tree = config_module.tree
        else:
            config_path = ctx.root / CONFIG_RELPATH
            if not config_path.exists():
                return []  # fixture/partial tree without the config
            try:
                tree = ast.parse(config_path.read_text(encoding="utf-8"))
            except SyntaxError:
                return []
        fields = _config_fields(tree)
        field_envs = {env for env, _ in fields.values()}
        known_envs = field_envs | _code_read_envs(ctx.root)

        findings: list[Finding] = []

        # 1) every config field's env var must be documented somewhere
        doc_text = self._doc_text(ctx.root)
        for name, (env, line) in sorted(fields.items()):
            if env not in doc_text:
                findings.append(Finding(
                    rule=self.id, path=CONFIG_RELPATH, line=line,
                    symbol=f"OperatorConfig.{name}",
                    message=(
                        f"config field `{name}` (env `{env}`) is not "
                        "documented in README.md or docs/ — an invisible "
                        "knob; add it to the README env table (or a docs "
                        "page)"
                    ),
                ))

        # 2) deploy env rows must be consumed by the code
        for yaml_path in sorted(ctx.root.glob("deploy/**/*.yaml")):
            rel = yaml_path.relative_to(ctx.root).as_posix()
            for lineno, line in enumerate(
                yaml_path.read_text(encoding="utf-8", errors="replace")
                .splitlines(),
                start=1,
            ):
                match = _YAML_ENV_ROW.match(line)
                if match and match.group(1) not in known_envs:
                    findings.append(Finding(
                        rule=self.id, path=rel, line=lineno,
                        symbol=match.group(1),
                        message=(
                            f"deploy env row `{match.group(1)}` matches no "
                            "OperatorConfig field and no os.environ read — "
                            "a dead setting (renamed field?); fix the name "
                            "or delete the row"
                        ),
                    ))

        # 3) README env-table rows must name envs something reads
        readme = ctx.root / "README.md"
        if readme.exists():
            for lineno, line in enumerate(
                readme.read_text(encoding="utf-8", errors="replace")
                .splitlines(),
                start=1,
            ):
                if not _README_ROW.match(line):
                    continue
                first_cell = line.split("|")[1]
                for env in _BACKTICKED_ENV.findall(first_cell):
                    if env not in known_envs:
                        findings.append(Finding(
                            rule=self.id, path="README.md", line=lineno,
                            symbol=env,
                            message=(
                                f"README env-table row documents `{env}`, "
                                "which no config field or os.environ read "
                                "consumes — the knob does not exist"
                            ),
                        ))
        return findings

    @staticmethod
    def _doc_text(root: Path) -> str:
        blobs = []
        readme = root / "README.md"
        if readme.exists():
            blobs.append(readme.read_text(encoding="utf-8", errors="replace"))
        for path in sorted(root.glob("docs/*.md")):
            blobs.append(path.read_text(encoding="utf-8", errors="replace"))
        return "\n".join(blobs)
