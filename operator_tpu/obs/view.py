"""Render a flight-recorder JSONL dump as flame-style text trees.

    python -m operator_tpu.obs.view dump.jsonl            # summary list
    python -m operator_tpu.obs.view dump.jsonl <trace-id> # one full tree
    python -m operator_tpu.obs.view dump.jsonl --all      # every tree
    python -m operator_tpu.obs.view dump.jsonl --blackbox # black-box only
    python -m operator_tpu.obs.view --steps dump.jsonl    # step timeline
    python -m operator_tpu.obs.view --slo ledger.jsonl    # SLO attainment

Reads the journal written by :class:`..record.FlightRecorder` (or a
black-box dump) and renders each trace's span tree with offsets/widths
scaled to the root span — the laptop-side twin of ``GET /traces/{id}``.

``--steps`` instead renders the step-clock timeline (docs/OBSERVABILITY.md
"Step clock") as a fixed-width table: the input is either a JSONL of raw
step-record dicts, or a black-box dump whose records carry a last-N
``steps`` tail in their ``extra`` context (the engine attaches one
automatically) — both are recognised line by line.

``--slo`` renders an SLO-ledger journal (docs/OBSERVABILITY.md "SLO
ledger"): the per-class attainment/goodput table plus the worst
offenders — the biggest misses, each with its flight-recorder stage
timeline so the report shows WHERE a missed analysis spent its budget.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional

from .record import FlightRecorder, TraceRecord, render_tree
from .steptrace import StepRecord, attribution, render_steps


def load_steps(path: str) -> list[StepRecord]:
    """Step records from a JSONL file: raw step-record dicts (one per
    line, as ``StepRecord.to_dict`` writes them) and/or black-box trace
    records whose ``extra.steps`` carries the engine's last-N tail.
    Unparseable lines are skipped — a step view over a crashed run's
    half-written journal should show what IS there."""
    steps: list[StepRecord] = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                data = json.loads(line)
            except json.JSONDecodeError:
                continue
            if not isinstance(data, dict):
                continue
            if "kind" in data and "device_ms" in data:
                steps.append(StepRecord.from_dict(data))
                continue
            extra = data.get("extra")
            if isinstance(extra, dict):
                for item in extra.get("steps") or []:
                    if isinstance(item, dict):
                        steps.append(StepRecord.from_dict(item))
    return steps


def _print_steps(path: str) -> int:
    try:
        steps = load_steps(path)
    except FileNotFoundError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    if not steps:
        print(f"no step records in {path}")
        return 0
    print(render_steps(steps))
    summary = attribution(steps)
    fractions = summary["fractions"]
    if fractions["host_gap"] is not None:
        print(
            f"\n{summary['steps']} steps  tokens={summary['tokens']}  "
            f"host_gap={fractions['host_gap']:.1%}  "
            f"device={fractions['device']:.1%}  "
            f"sample_xfer={fractions['sample_xfer']:.1%}"
        )
    return 0


def _print_slo(path: str, *, worst: int = 5) -> int:
    """Per-class attainment table + worst-offender timelines from an
    SLO-ledger journal (obs/sloledger.py)."""
    from .sloledger import SLOLedger, summarize

    try:
        records = SLOLedger.load_records(path)
    except FileNotFoundError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    if not records:
        print(f"no SLO records in {path}")
        return 0
    summary = summarize(records)
    header = (
        f"{'class':<14}{'target':>8}{'admit':>7}{'attain':>7}{'rate':>8}"
        f"{'shed':>6}{'dl-ex':>6}{'fail':>6}{'p50':>9}{'p95':>9}"
        f"{'goodput/min':>12}"
    )
    print(header)
    print("-" * len(header))

    def _row(name: str, row: dict, target: Optional[float]) -> None:
        rate = row.get("attainment")
        target_txt = f"{target:.0f}s" if target is not None else "-"
        rate_txt = f"{rate:.1%}" if rate is not None else "-"
        p50 = row["p50_s"]
        p95 = row["p95_s"]
        p50_txt = f"{p50:.3f}s" if p50 is not None else "-"
        p95_txt = f"{p95:.3f}s" if p95 is not None else "-"
        print(
            f"{name:<14}{target_txt:>8}"
            f"{row['admitted']:>7}{row['attained']:>7}{rate_txt:>8}"
            f"{row['shed']:>6}{row['deadline_exceeded']:>6}{row['failed']:>6}"
            f"{p50_txt:>9}{p95_txt:>9}"
            f"{row['goodput_analyses_per_min']:>12.1f}"
        )

    for cls, row in summary["classes"].items():
        _row(cls, row, row.get("target_s"))
    _row("TOTAL", summary["total"], None)

    misses = sorted(
        (r for r in records if not r.attained),
        key=lambda r: (
            (r.latency_s or 0.0) / r.target_s if r.target_s > 0 else 0.0
        ),
        reverse=True,
    )[:worst]
    if misses:
        print(f"\nworst offenders ({len(misses)} of "
              f"{sum(1 for r in records if not r.attained)} misses):")
        for record in misses:
            latency = record.latency_s or 0.0
            over = latency / record.target_s if record.target_s > 0 else 0.0
            print(
                f"  {record.trace_id}  {record.cls:<12} {record.outcome:<18}"
                f" {latency:8.3f}s / {record.target_s:.0f}s target"
                f" ({over:.1f}x)"
                + (f"  replica={record.replica}" if record.replica else "")
            )
            if record.stages:
                total = sum(record.stages.values()) or 1.0
                for name, ms in sorted(
                    record.stages.items(), key=lambda kv: -kv[1]
                ):
                    bar = "#" * max(1, round(ms / total * 30))
                    print(f"      {name:<16}{ms:>10.1f}ms  {bar}")
    return 0


def _print_record(record: TraceRecord, *, full: bool) -> None:
    if record.blackbox:
        print(f"*** BLACK BOX: {record.reason} ***")
        if record.extra:
            print(f"    context: {json.dumps(record.extra, sort_keys=True)}")
    if full:
        print(render_tree(record.trace))
    else:
        summary = record.summary()
        print(
            f"{summary['traceId']}  {summary.get('name', '?'):<20}"
            f" {float(summary.get('durationMs') or 0.0):>9.1f}ms"
            f"  spans={summary['spans']}  status={summary.get('status', '?')}"
            + ("  [blackbox]" if record.blackbox else "")
        )


def main(argv: Optional[list[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="operator_tpu.obs.view",
        description="render a flight-recorder JSONL dump as span trees",
    )
    parser.add_argument("path", help="trace journal / black-box JSONL")
    parser.add_argument("trace_id", nargs="?",
                        help="render only this trace (full tree)")
    parser.add_argument("--all", action="store_true",
                        help="render every trace as a full tree")
    parser.add_argument("--blackbox", action="store_true",
                        help="only black-box records")
    parser.add_argument("--steps", action="store_true",
                        help="render the step-clock timeline instead of "
                             "span trees (raw step JSONL or black-box "
                             "dumps with a steps tail)")
    parser.add_argument("--slo", action="store_true",
                        help="render an SLO-ledger journal: per-class "
                             "attainment table + worst-offender stage "
                             "timelines")
    parser.add_argument("--worst", type=int, default=5,
                        help="worst offenders to detail with --slo "
                             "(default 5)")
    args = parser.parse_args(argv)
    if args.slo:
        return _print_slo(args.path, worst=max(0, args.worst))
    if args.steps:
        return _print_steps(args.path)
    try:
        records = FlightRecorder.load(args.path)
    except FileNotFoundError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    if args.blackbox:
        records = [r for r in records if r.blackbox]
    if args.trace_id:
        records = [r for r in records if r.trace_id.startswith(args.trace_id)]
        if not records:
            print(f"error: no trace matching {args.trace_id!r} in {args.path}",
                  file=sys.stderr)
            return 1
    if not records:
        print(f"no traces in {args.path}")
        return 0
    full = bool(args.trace_id or args.all)
    try:
        for record in records:
            _print_record(record, full=full)
            if full:
                print()
    except BrokenPipeError:  # `... | head` closed the pipe mid-listing
        sys.stderr.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
