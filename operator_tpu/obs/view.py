"""Render a flight-recorder JSONL dump as flame-style text trees.

    python -m operator_tpu.obs.view dump.jsonl            # summary list
    python -m operator_tpu.obs.view dump.jsonl <trace-id> # one full tree
    python -m operator_tpu.obs.view dump.jsonl --all      # every tree
    python -m operator_tpu.obs.view dump.jsonl --blackbox # black-box only

Reads the journal written by :class:`..record.FlightRecorder` (or a
black-box dump) and renders each trace's span tree with offsets/widths
scaled to the root span — the laptop-side twin of ``GET /traces/{id}``.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional

from .record import FlightRecorder, TraceRecord, render_tree


def _print_record(record: TraceRecord, *, full: bool) -> None:
    if record.blackbox:
        print(f"*** BLACK BOX: {record.reason} ***")
        if record.extra:
            print(f"    context: {json.dumps(record.extra, sort_keys=True)}")
    if full:
        print(render_tree(record.trace))
    else:
        summary = record.summary()
        print(
            f"{summary['traceId']}  {summary.get('name', '?'):<20}"
            f" {float(summary.get('durationMs') or 0.0):>9.1f}ms"
            f"  spans={summary['spans']}  status={summary.get('status', '?')}"
            + ("  [blackbox]" if record.blackbox else "")
        )


def main(argv: Optional[list[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="operator_tpu.obs.view",
        description="render a flight-recorder JSONL dump as span trees",
    )
    parser.add_argument("path", help="trace journal / black-box JSONL")
    parser.add_argument("trace_id", nargs="?",
                        help="render only this trace (full tree)")
    parser.add_argument("--all", action="store_true",
                        help="render every trace as a full tree")
    parser.add_argument("--blackbox", action="store_true",
                        help="only black-box records")
    args = parser.parse_args(argv)
    try:
        records = FlightRecorder.load(args.path)
    except FileNotFoundError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    if args.blackbox:
        records = [r for r in records if r.blackbox]
    if args.trace_id:
        records = [r for r in records if r.trace_id.startswith(args.trace_id)]
        if not records:
            print(f"error: no trace matching {args.trace_id!r} in {args.path}",
                  file=sys.stderr)
            return 1
    if not records:
        print(f"no traces in {args.path}")
        return 0
    full = bool(args.trace_id or args.all)
    try:
        for record in records:
            _print_record(record, full=full)
            if full:
                print()
    except BrokenPipeError:  # `... | head` closed the pipe mid-listing
        sys.stderr.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
