"""Render a flight-recorder JSONL dump as flame-style text trees.

    python -m operator_tpu.obs.view dump.jsonl            # summary list
    python -m operator_tpu.obs.view dump.jsonl <trace-id> # one full tree
    python -m operator_tpu.obs.view dump.jsonl --all      # every tree
    python -m operator_tpu.obs.view dump.jsonl --blackbox # black-box only
    python -m operator_tpu.obs.view --steps dump.jsonl    # step timeline

Reads the journal written by :class:`..record.FlightRecorder` (or a
black-box dump) and renders each trace's span tree with offsets/widths
scaled to the root span — the laptop-side twin of ``GET /traces/{id}``.

``--steps`` instead renders the step-clock timeline (docs/OBSERVABILITY.md
"Step clock") as a fixed-width table: the input is either a JSONL of raw
step-record dicts, or a black-box dump whose records carry a last-N
``steps`` tail in their ``extra`` context (the engine attaches one
automatically) — both are recognised line by line.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional

from .record import FlightRecorder, TraceRecord, render_tree
from .steptrace import StepRecord, attribution, render_steps


def load_steps(path: str) -> list[StepRecord]:
    """Step records from a JSONL file: raw step-record dicts (one per
    line, as ``StepRecord.to_dict`` writes them) and/or black-box trace
    records whose ``extra.steps`` carries the engine's last-N tail.
    Unparseable lines are skipped — a step view over a crashed run's
    half-written journal should show what IS there."""
    steps: list[StepRecord] = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                data = json.loads(line)
            except json.JSONDecodeError:
                continue
            if not isinstance(data, dict):
                continue
            if "kind" in data and "device_ms" in data:
                steps.append(StepRecord.from_dict(data))
                continue
            extra = data.get("extra")
            if isinstance(extra, dict):
                for item in extra.get("steps") or []:
                    if isinstance(item, dict):
                        steps.append(StepRecord.from_dict(item))
    return steps


def _print_steps(path: str) -> int:
    try:
        steps = load_steps(path)
    except FileNotFoundError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    if not steps:
        print(f"no step records in {path}")
        return 0
    print(render_steps(steps))
    summary = attribution(steps)
    fractions = summary["fractions"]
    if fractions["host_gap"] is not None:
        print(
            f"\n{summary['steps']} steps  tokens={summary['tokens']}  "
            f"host_gap={fractions['host_gap']:.1%}  "
            f"device={fractions['device']:.1%}  "
            f"sample_xfer={fractions['sample_xfer']:.1%}"
        )
    return 0


def _print_record(record: TraceRecord, *, full: bool) -> None:
    if record.blackbox:
        print(f"*** BLACK BOX: {record.reason} ***")
        if record.extra:
            print(f"    context: {json.dumps(record.extra, sort_keys=True)}")
    if full:
        print(render_tree(record.trace))
    else:
        summary = record.summary()
        print(
            f"{summary['traceId']}  {summary.get('name', '?'):<20}"
            f" {float(summary.get('durationMs') or 0.0):>9.1f}ms"
            f"  spans={summary['spans']}  status={summary.get('status', '?')}"
            + ("  [blackbox]" if record.blackbox else "")
        )


def main(argv: Optional[list[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="operator_tpu.obs.view",
        description="render a flight-recorder JSONL dump as span trees",
    )
    parser.add_argument("path", help="trace journal / black-box JSONL")
    parser.add_argument("trace_id", nargs="?",
                        help="render only this trace (full tree)")
    parser.add_argument("--all", action="store_true",
                        help="render every trace as a full tree")
    parser.add_argument("--blackbox", action="store_true",
                        help="only black-box records")
    parser.add_argument("--steps", action="store_true",
                        help="render the step-clock timeline instead of "
                             "span trees (raw step JSONL or black-box "
                             "dumps with a steps tail)")
    args = parser.parse_args(argv)
    if args.steps:
        return _print_steps(args.path)
    try:
        records = FlightRecorder.load(args.path)
    except FileNotFoundError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    if args.blackbox:
        records = [r for r in records if r.blackbox]
    if args.trace_id:
        records = [r for r in records if r.trace_id.startswith(args.trace_id)]
        if not records:
            print(f"error: no trace matching {args.trace_id!r} in {args.path}",
                  file=sys.stderr)
            return 1
    if not records:
        print(f"no traces in {args.path}")
        return 0
    full = bool(args.trace_id or args.all)
    try:
        for record in records:
            _print_record(record, full=full)
            if full:
                print()
    except BrokenPipeError:  # `... | head` closed the pipe mid-listing
        sys.stderr.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
