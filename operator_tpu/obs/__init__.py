"""Per-analysis observability: tracing + flight recorder (docs/OBSERVABILITY.md).

``span.py`` holds the span/trace model and the ambient (contextvars)
tracer; ``record.py`` the bounded flight recorder with JSONL journaling
and black-box dumps; ``view.py`` the offline renderer
(``python -m operator_tpu.obs.view``).

Module defaults mirror :data:`..utils.timing.METRICS`: one process-wide
``RECORDER``/``TRACER`` pair (dependency-inject fresh ones in tests).
The default recorder honours ``TRACE_JOURNAL_PATH`` /
``TRACE_BLACKBOX_PATH`` so any run — including a CI chaos job — can be
told to leave a dump behind without touching construction sites.
"""

from __future__ import annotations

import os
from typing import Optional

from .record import FlightRecorder, TraceRecord, render_tree
from .sloledger import (
    DEFAULT_SLO_CLASSES,
    SLOBoard,
    SLOLedger,
    SLORecord,
    parse_slo_classes,
)
from .steptrace import StepRecord, StepRing, attribution, render_steps
from .span import (
    Span,
    Trace,
    Tracer,
    annotate,
    annotate_root,
    current_span,
    current_trace_id,
    current_traceparent,
    format_traceparent,
    parse_traceparent,
    span,
    stage_durations,
)

__all__ = [
    "DEFAULT_SLO_CLASSES",
    "FlightRecorder",
    "RECORDER",
    "SLOBoard",
    "SLOLedger",
    "SLORecord",
    "Span",
    "StepRecord",
    "StepRing",
    "Trace",
    "TraceRecord",
    "Tracer",
    "TRACER",
    "annotate",
    "annotate_root",
    "attribution",
    "build_tracer",
    "parse_slo_classes",
    "render_steps",
    "current_span",
    "current_trace_id",
    "current_traceparent",
    "format_traceparent",
    "parse_traceparent",
    "render_tree",
    "span",
    "stage_durations",
]

def _env_capacity(default: int = 256) -> int:
    try:
        return int(os.environ.get("TRACE_RING_CAPACITY", "") or default)
    except ValueError:  # garbage env must not fail every importer
        return default


#: process-wide defaults (tests inject their own)
RECORDER = FlightRecorder(
    capacity=_env_capacity(),
    path=os.environ.get("TRACE_JOURNAL_PATH") or None,
    blackbox_path=os.environ.get("TRACE_BLACKBOX_PATH") or None,
)
TRACER = Tracer(recorder=RECORDER)


def build_tracer(config, metrics=None) -> "tuple[Tracer, Optional[FlightRecorder]]":
    """(tracer, recorder) from an OperatorConfig — the operator's wiring
    path (operator/app.py).  ``obs_enabled=False`` returns a recorder-less
    tracer: spans still time (they are how stage code reads its own
    elapsed), traces are dropped on completion."""
    if not getattr(config, "obs_enabled", True):
        return Tracer(recorder=None), None
    recorder = FlightRecorder(
        capacity=getattr(config, "trace_ring_capacity", 256),
        path=getattr(config, "trace_journal_path", None) or None,
        blackbox_path=getattr(config, "trace_blackbox_path", None) or None,
        metrics=metrics,
    )
    return Tracer(recorder=recorder), recorder
