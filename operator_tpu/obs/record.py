"""Flight recorder — bounded trace ring + append-only JSONL + black-box dumps.

Every completed analysis trace lands in a bounded in-memory ring (the
recent history ``GET /traces`` serves) and, when a journal path is
configured, appends one JSONL line — the same crash-safe discipline as
``memory/store.py``: write + flush per record, torn tail lines detected
and skipped at load, losing at most the one trace that was mid-write.

A **black-box dump** is the full trace plus its failure context (deadline
ledger, fault-plan seed/fingerprint) written the moment an analysis ends
``deadline-exceeded``, a circuit breaker opens, or the serving engine
reports a device error — the replayable record that turns "the counter
went up" into "the budget died HERE" (docs/OBSERVABILITY.md).

Counters (docs/METRICS.md): ``podmortem_trace_recorded_total``,
``podmortem_trace_blackbox_total``, ``podmortem_trace_evicted_total`` —
each carrying the most recent trace id as an OpenMetrics exemplar so an
alert links straight to ``GET /traces/{id}``.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ..utils.journal import Journal
from ..utils.timing import METRICS, MetricsRegistry
from .span import Trace

log = logging.getLogger(__name__)

__all__ = ["FlightRecorder", "TraceRecord", "render_tree"]


@dataclass
class TraceRecord:
    """One remembered trace: the serialized span tree plus recorder
    metadata (wall-clock anchor, black-box marking)."""

    trace: dict
    recorded_at: float = 0.0
    blackbox: bool = False
    reason: Optional[str] = None
    extra: dict = field(default_factory=dict)

    @property
    def trace_id(self) -> str:
        return self.trace.get("traceId", "")

    def summary(self) -> dict:
        out = {
            "traceId": self.trace_id,
            "name": self.trace.get("name"),
            "durationMs": self.trace.get("durationMs"),
            "status": self.trace.get("status"),
            "spans": len(self.trace.get("spans") or []),
            "recordedAt": self.recorded_at,
        }
        if self.blackbox:
            out["blackbox"] = True
            out["reason"] = self.reason
        return out

    def to_dict(self) -> dict:
        out = {"recordedAt": self.recorded_at, "trace": self.trace}
        if self.blackbox:
            out["blackbox"] = True
            out["reason"] = self.reason
            out["extra"] = dict(self.extra)
        return out


class FlightRecorder:
    """Thread-safe bounded ring of completed traces, newest last."""

    def __init__(
        self,
        capacity: int = 256,
        *,
        path: Optional[str] = None,
        blackbox_path: Optional[str] = None,
        metrics: Optional[MetricsRegistry] = None,
        clock: Optional[Callable[[], float]] = None,
    ) -> None:
        self.capacity = max(1, capacity)
        self.path = path
        #: black-box dumps go here; falls back to the main journal so a
        #: recorder configured with only ``path`` still persists dumps
        self.blackbox_path = blackbox_path or path
        self.metrics = metrics or METRICS
        self._clock = clock or time.time
        self._lock = threading.Lock()
        self._ring: "OrderedDict[str, TraceRecord]" = OrderedDict()
        # journal appends ride the shared durable-journal helper
        # (utils/journal.py) in writer-thread mode: record() runs on the
        # asyncio event loop (the tracer's context exit), and a per-trace
        # write+flush on a slow disk — the exact condition black-box
        # forensics target — must stall the writer thread, never the
        # loop.  One Journal per distinct path (the black-box path
        # defaults to the main journal, sharing its instance) keeps
        # append order per file; pending writes drain via flush().
        # Folding onto the helper (ROADMAP leftover, PR 6) means the
        # torn-line/compaction discipline can no longer drift from the
        # incident store's and claim ledger's.
        self._journals: dict[str, "Journal"] = {}
        for journal_path in {
            p for p in (self.path, self.blackbox_path) if p
        }:
            journal = Journal(
                journal_path, label="flight-recorder", async_writes=True
            )
            journal.open()
            self._journals[journal_path] = journal

    # -- ingest --------------------------------------------------------
    def record(self, trace: "Trace | dict") -> TraceRecord:
        """Remember one completed trace (called by the Tracer on trace
        end, possibly from worker threads).

        A black-box record already holding this trace id is NEVER
        replaced: W3C semantics keep one id across a distributed
        transaction, and the analysis's trace id is published (CR status,
        outbound traceparent) — a later request echoing it back must not
        erase forensic evidence from the ring.  The new trace still
        journals to disk."""
        payload = trace.to_dict() if isinstance(trace, Trace) else dict(trace)
        record = TraceRecord(trace=payload, recorded_at=self._clock())
        with self._lock:
            existing = self._ring.get(record.trace_id)
            if existing is not None and existing.blackbox:
                record = existing
            else:
                self._ring[record.trace_id] = record
                self._ring.move_to_end(record.trace_id)
            evicted = self._evict_locked()
        self.metrics.incr("trace_recorded", exemplar=record.trace_id)
        if evicted:
            self.metrics.incr("trace_evicted", evicted)
        self._append(self.path, {"recordedAt": self._clock(), "trace": payload})
        return record

    def _evict_locked(self) -> int:
        """Shrink to capacity, preferring non-black-box victims: dumps are
        the records /traces exists for, so routine (or adversarial
        traceparent-minted) traffic cannot churn them out.  At most half
        the ring stays pinned — beyond that the oldest dump goes too,
        keeping the bound hard."""
        evicted = 0
        pin_limit = max(1, self.capacity // 2)
        while len(self._ring) > self.capacity:
            victim = None
            pinned = 0
            for trace_id, rec in self._ring.items():  # oldest first
                if rec.blackbox and pinned < pin_limit:
                    pinned += 1
                    continue
                victim = trace_id
                break
            if victim is None:  # all remaining are pinned dumps
                victim = next(iter(self._ring))
            self._ring.pop(victim)
            evicted += 1
        return evicted

    def black_box(
        self, trace_id: str, reason: str, extra: Optional[dict] = None
    ) -> Optional[TraceRecord]:
        """Mark a recorded trace as a black-box event and dump it in full
        (trace + reason + context) to the black-box JSONL.  Returns the
        record, or None when the trace already fell off the ring."""
        with self._lock:
            record = self._ring.get(trace_id)
            if record is None:
                return None
            record.blackbox = True
            record.reason = reason
            if extra:
                record.extra.update(extra)
            payload = record.to_dict()
        self.metrics.incr("trace_blackbox", exemplar=trace_id)
        self._append(self.blackbox_path, payload)
        return record

    def _append(self, path: Optional[str], payload: dict) -> None:
        """Enqueue one record to the path's journal (Journal serializes
        NOW — the record is live and mutated under the ring lock — and
        writes on its writer thread; IO failure is logged, never raised:
        a full disk must not fail the analysis being recorded)."""
        if not path:
            return
        journal = self._journals.get(path)
        if journal is not None:
            journal.append(payload)

    def flush(self, timeout: Optional[float] = 5.0) -> None:
        """Barrier: returns once every previously submitted journal write
        has hit disk (tests, pre-shutdown forensics)."""
        for journal in self._journals.values():
            journal.flush(timeout)

    # -- queries -------------------------------------------------------
    def get(self, trace_id: str) -> Optional[TraceRecord]:
        with self._lock:
            return self._ring.get(trace_id)

    def traces(
        self, limit: Optional[int] = None, *, blackbox_only: bool = False
    ) -> list[TraceRecord]:
        """Newest-first records (bounded by ``limit``)."""
        with self._lock:
            records = list(reversed(self._ring.values()))
        if blackbox_only:
            records = [r for r in records if r.blackbox]
        if limit is not None:
            records = records[: max(0, limit)]
        return records

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    # -- reload --------------------------------------------------------
    @staticmethod
    def load(path: str) -> list[TraceRecord]:
        """Parse a journal/black-box JSONL back into records, skipping
        torn or corrupt lines (same tolerance as the incident journal —
        a crash mid-append loses one line, never the dump).

        Records are deduped by trace id: with ``blackbox_path`` defaulting
        to the journal, a dumped trace appears twice (the plain record,
        then its black-box twin) — the dump supersedes; for plain
        duplicates (a rejoined remote trace id) the latest wins."""
        records: list[TraceRecord] = []
        dropped = 0
        try:
            handle = open(path, encoding="utf-8", errors="replace")
        except OSError as exc:
            raise FileNotFoundError(f"cannot read trace dump {path}: {exc}") from exc
        with handle:
            for line in handle:
                line = line.strip()
                if not line:
                    continue
                try:
                    data = json.loads(line)
                    trace = data["trace"]
                    if not isinstance(trace, dict) or "traceId" not in trace:
                        raise KeyError("trace")
                except (ValueError, KeyError, TypeError):
                    dropped += 1
                    continue
                records.append(
                    TraceRecord(
                        trace=trace,
                        recorded_at=float(data.get("recordedAt") or 0.0),
                        blackbox=bool(data.get("blackbox")),
                        reason=data.get("reason"),
                        extra=dict(data.get("extra") or {}),
                    )
                )
        if dropped:
            log.warning("trace dump %s: skipped %d corrupt line(s)", path, dropped)
        deduped: "OrderedDict[str, TraceRecord]" = OrderedDict()
        for record in records:
            previous = deduped.get(record.trace_id)
            if previous is not None and previous.blackbox and not record.blackbox:
                continue  # never let a plain twin shadow the dump
            deduped[record.trace_id] = record  # keeps first-seen position
        return list(deduped.values())


# --------------------------------------------------------------------------
# rendering (shared by the view CLI and GET /traces/{id})
# --------------------------------------------------------------------------

_BAR_WIDTH = 24


def _render_span(
    span: dict,
    by_parent: dict[Optional[str], list[dict]],
    root_start: int,
    root_ms: float,
    depth: int,
    lines: list[str],
) -> None:
    duration = float(span.get("durationMs") or 0.0)
    offset_ms = (int(span.get("startNs") or 0) - root_start) / 1e6
    pct = (duration / root_ms * 100.0) if root_ms > 0 else 0.0
    # flame-style bar: position = offset within the root, width = share
    lead = int(offset_ms / root_ms * _BAR_WIDTH) if root_ms > 0 else 0
    width = max(1, int(duration / root_ms * _BAR_WIDTH)) if root_ms > 0 else 1
    lead = min(lead, _BAR_WIDTH - 1)
    width = min(width, _BAR_WIDTH - lead)
    bar = " " * lead + "█" * width + " " * (_BAR_WIDTH - lead - width)
    marker = " !" if span.get("status") == "error" else ""
    attrs = span.get("attributes") or {}
    attr_text = ""
    if attrs:
        shown = ", ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
        attr_text = f"  [{shown[:120]}]"
    lines.append(
        f"{'  ' * depth}{span.get('name', '?'):<{max(4, 28 - 2 * depth)}}"
        f" {duration:>9.1f}ms {pct:>5.1f}% |{bar}|{marker}{attr_text}"
    )
    for child in by_parent.get(span.get("spanId"), []):
        _render_span(child, by_parent, root_start, root_ms, depth + 1, lines)


def render_tree(trace: dict) -> str:
    """Flame-style text tree of one serialized trace — offsets and widths
    scaled to the root span, children indented under their parents."""
    spans = list(trace.get("spans") or [])
    if not spans:
        return f"trace {trace.get('traceId', '?')}: no spans"
    spans.sort(key=lambda s: int(s.get("startNs") or 0))
    roots = [s for s in spans if not s.get("parentId")]
    root = roots[0] if roots else spans[0]
    by_parent: dict[Optional[str], list[dict]] = {}
    for span in spans:
        if span is root:
            continue
        by_parent.setdefault(span.get("parentId"), []).append(span)
    root_ms = float(root.get("durationMs") or 0.0)
    header = (
        f"trace {trace.get('traceId', '?')}  {trace.get('name', '?')}"
        f"  {root_ms:.1f}ms  status={trace.get('status', '?')}"
    )
    lines = [header]
    _render_span(root, by_parent, int(root.get("startNs") or 0), root_ms, 0, lines)
    # orphans (parent span fell outside the dump) still render, flat
    known = {s.get("spanId") for s in spans}
    for span in spans:
        parent = span.get("parentId")
        if span is not root and parent and parent not in known:
            _render_span(span, by_parent, int(root.get("startNs") or 0),
                         root_ms, 1, lines)
    return "\n".join(lines)
