"""Step clock: bounded per-step records for the serving decode loops.

BENCH_r02 measured decode MFU 0.0064 — the chip is ~99% idle during
decode — and a single opaque MFU number cannot say *where* a step's wall
time goes.  Both engine loops (the wave engine's ``step()`` and the
continuous scheduler's ``Scheduler.step()``) record one
:class:`StepRecord` per dispatched step into a bounded :class:`StepRing`,
splitting the step's monotonic timeline into three attributed components:

- ``host_gap_ms``   — time between the previous step's commit and this
  step's dispatch (host think-time: scheduling, admission, Python)
- ``device_ms``     — dispatch → result ready (``block_until_ready`` on
  the already-dispatched token array; the ONE sync the loop was about to
  perform anyway, so the clock adds zero new host syncs — GL001-gated)
- ``sample_xfer_ms``— the sampled-token device→host fetch

Attribution fractions are computed over the SUM of the three components,
so they always total 1.0 by construction; the analytic flops-per-token
model (serving/perf.py) turns the same records into per-step achieved
TFLOPs and a measured, attributed decode MFU.

The ring is host-side bookkeeping only and is never reachable from a
compiled program; ``STEP_RING_CAPACITY`` bounds it (default 512 steps).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Iterable, Optional, Sequence

#: record kinds: a pure-prefill step, a pure-decode step, or the
#: continuous scheduler's ragged mixed step (both phases in one program)
STEP_KINDS = ("prefill", "decode", "mixed")

_DEFAULT_CAPACITY = 512


def _env_capacity(default: int = _DEFAULT_CAPACITY) -> int:
    try:
        return int(os.environ.get("STEP_RING_CAPACITY", "") or default)
    except ValueError:  # garbage env must not fail every importer
        return default


@dataclass(frozen=True)
class StepRecord:
    """One engine step's attributed timeline (immutable once recorded)."""

    seq: int
    kind: str  # "prefill" | "decode" | "mixed"
    tokens: int  # tokens processed this step (decode rows / prefill chunk)
    slots: int  # live slots at dispatch
    occupancy: float  # slots / max_slots
    host_gap_ms: float
    device_ms: float
    sample_xfer_ms: float
    #: per-step achieved MFU when the ring's owner knows the model's
    #: flops/token (serving/perf.py StepClock); None on bare rings
    mfu: Optional[float] = None
    #: generated tokens actually COMMITTED this step — differs from
    #: ``tokens`` under speculation (a verify row is billed q_count
    #: tokens of compute but lands accept+1) and under pipelining
    #: (voided work lands zero); None on engines that don't distinguish
    accepted: Optional[int] = None
    #: prompt tokens served from the prefix KV cache by rows admitted at
    #: this step (serving/kvstore.py) — kept off the billed ``tokens``
    #: so MFU stays honest on compute actually performed; None on
    #: engines without a prefix cache
    cached_tokens: Optional[int] = None

    @property
    def total_ms(self) -> float:
        return self.host_gap_ms + self.device_ms + self.sample_xfer_ms

    def to_dict(self) -> dict:
        out = {
            "seq": self.seq,
            "kind": self.kind,
            "tokens": self.tokens,
            "slots": self.slots,
            "occupancy": round(self.occupancy, 4),
            "host_gap_ms": round(self.host_gap_ms, 4),
            "device_ms": round(self.device_ms, 4),
            "sample_xfer_ms": round(self.sample_xfer_ms, 4),
        }
        if self.mfu is not None:
            out["mfu"] = round(self.mfu, 6)
        if self.accepted is not None:
            out["accepted"] = self.accepted
        if self.cached_tokens is not None:
            out["cached_tokens"] = self.cached_tokens
        return out

    @classmethod
    def from_dict(cls, data: dict) -> "StepRecord":
        return cls(
            seq=int(data.get("seq", 0)),
            kind=str(data.get("kind", "decode")),
            tokens=int(data.get("tokens", 0)),
            slots=int(data.get("slots", 0)),
            occupancy=float(data.get("occupancy", 0.0)),
            host_gap_ms=float(data.get("host_gap_ms", 0.0)),
            device_ms=float(data.get("device_ms", 0.0)),
            sample_xfer_ms=float(data.get("sample_xfer_ms", 0.0)),
            mfu=(float(data["mfu"]) if data.get("mfu") is not None else None),
            accepted=(
                int(data["accepted"])
                if data.get("accepted") is not None else None
            ),
            cached_tokens=(
                int(data["cached_tokens"])
                if data.get("cached_tokens") is not None else None
            ),
        )


class StepRing:
    """Bounded, thread-safe ring of step records.

    Recorded from the decode worker thread, read from the event loop
    (``/healthz`` summaries, black-box dumps) — hence the lock.  Besides
    the bounded window it keeps MONOTONIC cumulative totals per kind:
    eviction-proof running sums the engines use to derive a request's
    decode wall time from the clock itself (so span timings and step
    records can never disagree, however long the generation ran).
    """

    def __init__(self, capacity: Optional[int] = None) -> None:
        self.capacity = (
            int(capacity) if capacity and int(capacity) > 0 else _env_capacity()
        )
        self._lock = threading.Lock()
        self._records: list[StepRecord] = []
        self._seq = 0
        self.evicted = 0
        #: cumulative attributed ms per kind since construction (never
        #: reset by eviction; reset() zeroes them with the ring)
        self.cum_ms = {kind: 0.0 for kind in STEP_KINDS}
        self.cum_tokens = {kind: 0 for kind in STEP_KINDS}

    def append(
        self,
        *,
        kind: str,
        tokens: int,
        slots: int,
        occupancy: float,
        host_gap_ms: float,
        device_ms: float,
        sample_xfer_ms: float,
        mfu: Optional[float] = None,
        accepted: Optional[int] = None,
        cached_tokens: Optional[int] = None,
    ) -> StepRecord:
        if kind not in STEP_KINDS:
            raise ValueError(f"unknown step kind {kind!r} (one of {STEP_KINDS})")
        with self._lock:
            record = StepRecord(
                seq=self._seq,
                kind=kind,
                tokens=int(tokens),
                slots=int(slots),
                occupancy=float(occupancy),
                host_gap_ms=max(0.0, float(host_gap_ms)),
                device_ms=max(0.0, float(device_ms)),
                sample_xfer_ms=max(0.0, float(sample_xfer_ms)),
                mfu=mfu,
                accepted=(int(accepted) if accepted is not None else None),
                cached_tokens=(
                    int(cached_tokens) if cached_tokens is not None else None
                ),
            )
            self._seq += 1
            self._records.append(record)
            if len(self._records) > self.capacity:
                del self._records[0]
                self.evicted += 1
            self.cum_ms[kind] += record.total_ms
            self.cum_tokens[kind] += record.tokens
            return record

    def records(self, last: Optional[int] = None) -> "list[StepRecord]":
        with self._lock:
            if last is not None and last >= 0:
                return self._records[-last:] if last else []
            return list(self._records)

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    @property
    def decode_cum_ms(self) -> float:
        """Cumulative attributed wall of every decode-bearing step (pure
        decode + mixed) — the monotonic clock request decode times are
        derived from."""
        with self._lock:
            return self.cum_ms["decode"] + self.cum_ms["mixed"]

    def reset(self) -> None:
        with self._lock:
            self._records.clear()
            self._seq = 0
            self.evicted = 0
            for kind in STEP_KINDS:
                self.cum_ms[kind] = 0.0
                self.cum_tokens[kind] = 0


def attribution(
    records: "Sequence[StepRecord]",
    *,
    flops_per_token: Optional[float] = None,
    peak_tflops: Optional[float] = None,
) -> dict:
    """Stall-attribution summary over a window of step records.

    Fractions are shares of the summed attributed time (host_gap +
    device + sample_xfer over all records), so they total 1.0 by
    construction.  With a flops model, ``decode_mfu`` is the measured
    MFU over decode-bearing steps (pure decode + mixed): tokens they
    produced x flops/token against peak over their attributed wall."""
    host_gap = sum(r.host_gap_ms for r in records)
    device = sum(r.device_ms for r in records)
    xfer = sum(r.sample_xfer_ms for r in records)
    total = host_gap + device + xfer
    decode_records = [r for r in records if r.kind in ("decode", "mixed")]
    decode_ms = sum(r.total_ms for r in decode_records)
    decode_tokens = sum(r.tokens for r in decode_records)
    # committed generated tokens: billed tokens unless the engine
    # reported a per-step accepted count (speculation / voided work)
    accepted_tokens = sum(
        r.accepted if r.accepted is not None else r.tokens
        for r in decode_records
    )
    out = {
        "steps": len(records),
        "prefill_steps": sum(1 for r in records if r.kind == "prefill"),
        "decode_steps": sum(1 for r in records if r.kind == "decode"),
        "mixed_steps": sum(1 for r in records if r.kind == "mixed"),
        "tokens": sum(r.tokens for r in records),
        "host_gap_ms": round(host_gap, 3),
        "device_ms": round(device, 3),
        "sample_xfer_ms": round(xfer, 3),
        "accepted_tokens": accepted_tokens,
        # prompt tokens the prefix cache spared from prefill compute
        "cached_tokens": sum(r.cached_tokens or 0 for r in records),
        "occupancy_avg": (
            round(sum(r.occupancy for r in records) / len(records), 4)
            if records else None
        ),
        "fractions": {
            "host_gap": round(host_gap / total, 4) if total else None,
            "device": round(device / total, 4) if total else None,
            "sample_xfer": round(xfer / total, 4) if total else None,
        },
        "decode_mfu": None,
        "achieved_tflops": None,
    }
    if flops_per_token and peak_tflops and decode_ms > 0 and decode_tokens:
        flops = decode_tokens * flops_per_token
        achieved = flops / (decode_ms / 1e3) / 1e12  # TFLOP/s
        out["achieved_tflops"] = round(achieved, 6)
        out["decode_mfu"] = round(achieved / peak_tflops, 6)
    return out


def render_steps(records: "Iterable[StepRecord]") -> str:
    """Compact fixed-width per-step timeline table (the ``obs.view
    --steps`` rendering; also readable when pasted from a black-box
    dump)."""
    header = (
        f"{'seq':>5}  {'kind':<7} {'tok':>5} {'slots':>5} {'occ':>5} "
        f"{'gap_ms':>8} {'dev_ms':>8} {'xfer_ms':>8} {'total':>8} {'mfu':>8}"
    )
    lines = [header, "-" * len(header)]
    for r in records:
        mfu = f"{r.mfu:.4f}" if r.mfu is not None else "-"
        lines.append(
            f"{r.seq:>5}  {r.kind:<7} {r.tokens:>5} {r.slots:>5} "
            f"{r.occupancy:>5.2f} {r.host_gap_ms:>8.3f} {r.device_ms:>8.3f} "
            f"{r.sample_xfer_ms:>8.3f} {r.total_ms:>8.3f} {mfu:>8}"
        )
    return "\n".join(lines)
