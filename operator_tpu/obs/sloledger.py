"""SLO ledger: per-request SLO classes, attainment, and goodput-under-SLO.

Every analysis (or serving request) is assigned an SLO class + latency
target at admission and recorded over its full lifetime; the ledger then
computes **attainment** (fraction of terminal requests that completed
within their target) and **goodput-under-SLO** (completed-within-target
tokens/s and analyses/min) per class, per replica, and fleet-wide — the
arbiter metric the open-loop storm harness (``operator_tpu/loadgen/``)
reports, the way DeepServe gates pre-warmed pools on SLO attainment and
xLLM judges its async scheduler on deadline satisfaction rather than raw
throughput (docs/PERF.md "Open-loop methodology").

Timings are NOT re-measured here: the ledger's stamps come from the same
injectable clock the deadline envelopes use, stage splits come from the
flight recorder's span tree (``stage_durations``), and serving-side token
latencies come from the step clock — one source of truth, no new host
syncs.  Terminal records journal with the shared ``utils/journal.py``
discipline (torn-line-tolerant load, ``python -m operator_tpu.obs.view
--slo <journal>`` renders them offline).

Two accounting surfaces:

- :class:`SLOLedger` — the operator/loadgen side: full per-request
  records, journaling, ``podmortem_slo_*`` counters and the attainment
  histogram.
- :class:`SLOBoard` — the serving-replica side: bounded per-class
  aggregates only (no journal, no metrics — the ledger owns counters, so
  an in-process operator+serving pair never double-counts), carried on
  ``GET /healthz`` via ``ServingEngine.load_report()`` and rolled up
  fleet-wide by the router's ``fleet_rollup`` / token-gated ``GET /fleet``.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..utils.journal import Journal

__all__ = [
    "DEFAULT_SLO_CLASSES",
    "SLO_OUTCOME_ATTR",
    "SLOBoard",
    "SLOLedger",
    "SLORecord",
    "parse_slo_classes",
    "summarize",
]

#: class spec default (config.slo_classes / env SLO_CLASSES):
#: ``name:target_seconds`` pairs, comma-separated
DEFAULT_SLO_CLASSES = "interactive:2,standard:30,batch:120"

#: root-span attribute a backend may set to OVERRIDE the ledger's outcome
#: inference — the storm harness stamps "shed" here when the router
#: refused the dispatch, so shed load is attributed as shed, not failed
SLO_OUTCOME_ATTR = "slo_outcome"

#: "degraded" is a DISTINCT terminal outcome (not conflated with
#: deadline-exceeded): the overload ladder truncated the analysis depth
#: but the request still finished — it attains its SLO when in budget
TERMINAL_OUTCOMES = ("completed", "degraded", "deadline-exceeded", "shed", "failed")

#: latency histogram bounds (ms): analysis SLO targets run to minutes, so
#: the serving DEFAULT_BUCKETS_MS top of 10s would dump every batch-class
#: observation into +Inf
SLO_LATENCY_BUCKETS_MS: "tuple[float, ...]" = (
    50.0, 100.0, 250.0, 500.0, 1000.0, 2000.0, 5000.0,
    10_000.0, 30_000.0, 60_000.0, 120_000.0, 300_000.0,
)

#: attainment histogram: latency as a PERCENT of the class target — the
#: cumulative mass at or under the 100 bucket IS the attainment rate, so
#: one histogram answers both "how close to the edge" and "what fraction
#: made it" per scrape window
SLO_TARGET_FRACTION_BUCKETS: "tuple[float, ...]" = (
    10.0, 25.0, 50.0, 75.0, 90.0, 100.0, 125.0, 150.0, 200.0, 400.0, 1000.0,
)


def parse_slo_classes(spec: Optional[str]) -> "dict[str, float]":
    """``"interactive:2,standard:30,batch:120"`` -> name->target-seconds.

    Malformed entries are skipped; an empty or fully-garbage spec falls
    back to :data:`DEFAULT_SLO_CLASSES` so a bad env var can never leave
    the ledger classless."""
    classes: dict[str, float] = {}
    for raw in (spec or "").replace(",", " ").split():
        name, _, target = raw.partition(":")
        try:
            target_s = float(target)
        except ValueError:
            continue
        if name and target_s > 0:
            classes[name] = target_s
    if not classes:
        for raw in DEFAULT_SLO_CLASSES.split(","):
            name, _, target = raw.partition(":")
            classes[name] = float(target)
    return classes


@dataclass
class SLORecord:
    """One request's SLO lifetime.  ``admitted_at``/``completed_at`` are
    on the ledger's (injectable, monotonic) clock; ``stages`` carries the
    flight-recorder stage splits (name -> ms) so the worst-offender view
    can show WHERE a miss spent its budget."""

    trace_id: str
    cls: str
    target_s: float
    admitted_at: float
    completed_at: Optional[float] = None
    latency_s: Optional[float] = None
    outcome: str = "pending"  # "pending" | TERMINAL_OUTCOMES
    attained: bool = False
    tokens: int = 0
    replica: Optional[str] = None
    stages: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "cls": self.cls,
            "target_s": round(self.target_s, 6),
            "admitted_at": round(self.admitted_at, 6),
            "completed_at": (
                round(self.completed_at, 6)
                if self.completed_at is not None else None
            ),
            "latency_s": (
                round(self.latency_s, 6) if self.latency_s is not None else None
            ),
            "outcome": self.outcome,
            "attained": self.attained,
            "tokens": self.tokens,
            "replica": self.replica,
            "stages": dict(self.stages),
        }

    @classmethod
    def from_dict(cls, data: dict) -> "SLORecord":
        return cls(
            trace_id=str(data.get("trace_id", "")),
            cls=str(data.get("cls", "default")),
            target_s=float(data.get("target_s") or 0.0),
            admitted_at=float(data.get("admitted_at") or 0.0),
            completed_at=(
                None if data.get("completed_at") is None
                else float(data["completed_at"])
            ),
            latency_s=(
                None if data.get("latency_s") is None
                else float(data["latency_s"])
            ),
            outcome=str(data.get("outcome", "pending")),
            attained=bool(data.get("attained")),
            tokens=int(data.get("tokens") or 0),
            replica=data.get("replica"),
            stages=dict(data.get("stages") or {}),
        )


def _percentile(sorted_vals: "list[float]", q: float) -> Optional[float]:
    """Nearest-rank percentile over an ascending list (deterministic, the
    definition the hand-valued tests replay)."""
    if not sorted_vals:
        return None
    rank = max(1, math.ceil(q / 100.0 * len(sorted_vals)))
    return sorted_vals[rank - 1]


def _bucket_summary(records: "list[SLORecord]") -> dict:
    """Aggregate one group of terminal records (a class, a replica, or
    the whole ledger) into the attainment/goodput row every surface
    shares."""
    admitted = len(records)
    completed = [r for r in records if r.outcome == "completed"]
    degraded = [r for r in records if r.outcome == "degraded"]
    attained = [r for r in records if r.attained]
    # degraded requests DID finish — their latencies belong in the
    # percentile view alongside full completions
    latencies = sorted(
        r.latency_s for r in completed + degraded if r.latency_s is not None
    )
    shed = sum(1 for r in records if r.outcome == "shed")
    deadline_exceeded = sum(
        1 for r in records if r.outcome == "deadline-exceeded"
    )
    failed = sum(1 for r in records if r.outcome == "failed")
    stamps = [r.admitted_at for r in records]
    ends = [r.completed_at for r in records if r.completed_at is not None]
    elapsed_s = max(ends) - min(stamps) if stamps and ends else 0.0
    tokens_attained = sum(r.tokens for r in attained)
    span = max(elapsed_s, 1e-9)
    return {
        "admitted": admitted,
        "completed": len(completed),
        "degraded": len(degraded),
        "attained": len(attained),
        "attainment": round(len(attained) / admitted, 6) if admitted else None,
        "shed": shed,
        "deadline_exceeded": deadline_exceeded,
        "failed": failed,
        "p50_s": _percentile(latencies, 50),
        "p95_s": _percentile(latencies, 95),
        "p99_s": _percentile(latencies, 99),
        "tokens_attained": tokens_attained,
        "goodput_tokens_s": (
            round(tokens_attained / span, 6) if attained else 0.0
        ),
        "goodput_analyses_per_min": (
            round(len(attained) * 60.0 / span, 6) if attained else 0.0
        ),
        "elapsed_s": round(elapsed_s, 6),
    }


def summarize(records: "list[SLORecord]") -> dict:
    """Attainment + goodput-under-SLO over terminal records: per class,
    per replica, and total.  Attainment counts EVERY terminal request in
    its denominator — shed and deadline-exceeded load counts against the
    SLO, which is the point of measuring open-loop (a closed-loop
    harness would simply not offer the load it can't carry)."""
    terminal = [r for r in records if r.outcome in TERMINAL_OUTCOMES]
    classes: dict[str, list[SLORecord]] = {}
    replicas: dict[str, list[SLORecord]] = {}
    for record in terminal:
        classes.setdefault(record.cls, []).append(record)
        if record.replica:
            replicas.setdefault(record.replica, []).append(record)
    out_classes = {}
    for cls in sorted(classes):
        row = _bucket_summary(classes[cls])
        row["target_s"] = classes[cls][0].target_s
        out_classes[cls] = row
    return {
        "classes": out_classes,
        "replicas": {
            rid: _bucket_summary(replicas[rid]) for rid in sorted(replicas)
        },
        "total": _bucket_summary(terminal),
    }


class SLOLedger:
    """Admission-to-terminal SLO accounting with journaling + metrics.

    ``admit`` stamps the class + target at admission (keyed by the
    flight-recorder trace id so ledger records join span trees and
    status entries on one id); ``finish`` computes latency and
    attainment, journals the terminal record, and bumps the
    ``podmortem_slo_*`` counters + histograms.  Single-threaded use
    (event loop / bench loop) — the journal's own thread contract
    applies."""

    def __init__(
        self,
        classes: Optional["dict[str, float]"] = None,
        *,
        default_class: Optional[str] = None,
        path: Optional[str] = None,
        metrics=None,
        clock: Optional[Callable[[], float]] = None,
    ) -> None:
        self.classes = dict(classes) if classes else parse_slo_classes(None)
        self.default_class = (
            default_class if default_class in self.classes
            else ("standard" if "standard" in self.classes
                  else next(iter(self.classes)))
        )
        self.metrics = metrics
        self._clock = clock or time.monotonic
        self._open: dict[str, SLORecord] = {}
        self._closed: list[SLORecord] = []
        # incremental per-class [terminal, attained] counts: the live
        # attainment feed the overload ladder's class protection reads
        # (O(classes), no rescan of _closed per admission decision)
        self._class_agg: dict[str, "list[int]"] = {}
        # async_writes: finish() runs inside the analysis pipeline's async
        # path — terminal-record appends must enqueue to the writer
        # thread, not block the event loop (graftlint GL006); close()
        # still barriers, so no record is lost on drain
        self._journal = (
            Journal(path, label="slo-ledger", async_writes=True)
            if path else None
        )
        if self._journal is not None:
            self._journal.open()

    # -- admission / terminal ------------------------------------------
    def admit(
        self,
        trace_id: str,
        *,
        cls: Optional[str] = None,
        target_s: Optional[float] = None,
        replica: Optional[str] = None,
    ) -> SLORecord:
        name = cls if cls in self.classes else self.default_class
        record = SLORecord(
            trace_id=trace_id,
            cls=name,
            target_s=(
                target_s if target_s is not None else self.classes[name]
            ),
            admitted_at=self._clock(),
            replica=replica,
        )
        self._open[trace_id] = record
        if self.metrics is not None:
            self.metrics.incr("slo_admitted")
        return record

    def finish(
        self,
        trace_id: str,
        *,
        outcome: str,
        tokens: int = 0,
        replica: Optional[str] = None,
        stages: Optional[dict] = None,
    ) -> Optional[SLORecord]:
        record = self._open.pop(trace_id, None)
        if record is None:
            return None
        if outcome not in TERMINAL_OUTCOMES:
            outcome = "failed"
        record.completed_at = self._clock()
        record.latency_s = max(0.0, record.completed_at - record.admitted_at)
        record.outcome = outcome
        record.tokens = int(tokens or 0)
        if replica is not None:
            record.replica = replica
        if stages:
            record.stages = dict(stages)
        # a degraded (depth-truncated) analysis that lands in budget still
        # attains — that trade IS the degradation ladder's point: smooth
        # attainment decay under storm instead of a reject cliff
        record.attained = (
            outcome in ("completed", "degraded")
            and record.latency_s <= record.target_s
        )
        self._closed.append(record)
        agg = self._class_agg.setdefault(record.cls, [0, 0])
        agg[0] += 1
        if record.attained:
            agg[1] += 1
        if self._journal is not None:
            self._journal.append(record.to_dict())
        m = self.metrics
        if m is not None:
            m.incr("slo_attained" if record.attained else "slo_missed")
            if outcome == "shed":
                m.incr("slo_shed")
            elif outcome == "degraded":
                m.incr("slo_degraded")
            elif outcome == "deadline-exceeded":
                m.incr("slo_deadline_exceeded")
            elif outcome == "failed":
                m.incr("slo_failed")
            m.observe(
                "slo_latency_milliseconds",
                record.latency_s * 1e3,
                buckets=SLO_LATENCY_BUCKETS_MS,
            )
            if record.target_s > 0:
                m.observe(
                    "slo_target_fraction_percent",
                    record.latency_s / record.target_s * 100.0,
                    buckets=SLO_TARGET_FRACTION_BUCKETS,
                )
        return record

    # -- reads ---------------------------------------------------------
    @property
    def pending(self) -> int:
        return len(self._open)

    @property
    def records(self) -> "list[SLORecord]":
        return list(self._closed)

    def attainment_by_class(self) -> "dict[str, Optional[float]]":
        """Live per-class attainment fraction over terminal records (None
        until a class has any) — the feed ``router/value.py``'s
        ValueModel protection reads, so "never shed the class already
        below its attainment target" tracks reality, not a snapshot."""
        out: dict[str, Optional[float]] = {}
        for cls, (terminal, attained) in self._class_agg.items():
            out[cls] = round(attained / terminal, 6) if terminal else None
        return out

    def pending_by_class(self) -> "dict[str, int]":
        depth: dict[str, int] = {}
        for record in self._open.values():
            depth[record.cls] = depth.get(record.cls, 0) + 1
        return depth

    def snapshot(self) -> dict:
        """The summary every surface shares, plus current queue state."""
        out = summarize(self._closed)
        out["pending"] = self.pending
        out["pending_by_class"] = self.pending_by_class()
        return out

    def close(self) -> None:
        if self._journal is not None:
            self._journal.close()

    # -- offline -------------------------------------------------------
    @staticmethod
    def load_records(path: str) -> "list[SLORecord]":
        """Terminal records from a ledger journal, torn-line tolerant
        (the view CLI and the CI smoke both read through here)."""
        records: list[SLORecord] = []
        journal = Journal(path, label="slo-ledger")
        journal.load(lambda data: records.append(SLORecord.from_dict(data)))
        return records


class SLOBoard:
    """Bounded per-class aggregates for ONE serving replica: what
    ``load_report()`` / ``GET /healthz`` carries and ``fleet_rollup``
    weights.  No journal, no record list, no metrics — O(classes) state
    however long the replica serves."""

    def __init__(self, *, clock: Optional[Callable[[], float]] = None) -> None:
        self._clock = clock or time.monotonic
        self._first: Optional[float] = None
        self._last: Optional[float] = None
        self._pending: dict[str, int] = {}
        self._agg: dict[str, "list[int]"] = {}  # cls -> [completed, attained]
        self.tokens_attained = 0
        self.completed = 0
        self.attained = 0

    def submitted(self, cls: str) -> None:
        if self._first is None:
            self._first = self._clock()
        self._pending[cls] = self._pending.get(cls, 0) + 1

    def finished(self, cls: str, *, attained: bool, tokens: int = 0) -> None:
        count = self._pending.get(cls, 0) - 1
        if count > 0:
            self._pending[cls] = count
        else:
            self._pending.pop(cls, None)
        row = self._agg.setdefault(cls, [0, 0])
        row[0] += 1
        self.completed += 1
        if attained:
            row[1] += 1
            self.attained += 1
            self.tokens_attained += max(0, int(tokens))
        self._last = self._clock()

    def attainment(self) -> Optional[float]:
        if not self.completed:
            return None
        return round(self.attained / self.completed, 6)

    def goodput_tokens_s(self) -> Optional[float]:
        if self._first is None or self._last is None:
            return None
        span = max(self._last - self._first, 1e-9)
        return round(self.tokens_attained / span, 6)

    def per_class(self) -> dict:
        classes = sorted(set(self._pending) | set(self._agg))
        out = {}
        for cls in classes:
            completed, attained = self._agg.get(cls, (0, 0))
            out[cls] = {
                "queued": self._pending.get(cls, 0),
                "completed": completed,
                "attained": attained,
                "attainment": (
                    round(attained / completed, 6) if completed else None
                ),
            }
        return out
