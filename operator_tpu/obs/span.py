"""Per-analysis distributed tracing — the span model and the tracer.

The aggregate stage percentiles in :mod:`..utils.timing` answer "is the
fleet fast?"; they cannot answer "where did THIS analysis's budget go?"
when one request blows its deadline or trips a breaker.  A :class:`Span`
is one timed region of one analysis (collect, parse, recall, the AI leg,
an engine generate, a kube call); a :class:`Trace` is the complete tree
for one analysis, identified by a W3C-shaped 16-byte trace id.

Propagation is **ambient** — the current span rides a ``contextvars``
context variable, exactly like the asyncio task context the pipeline
already runs in, so every stage, provider call, recall lookup and engine
request gets a span without a single new plumbing argument.  The context
flows through ``await`` and ``asyncio.to_thread`` (which copies the
context into the worker) for free; code running on executors that do NOT
copy context (the decode worker) is tied back in via span *tags* instead
(``SamplingParams.trace_tag`` -> ``jax.profiler.TraceAnnotation``).

Thread-safety: spans from concurrent tasks/threads of one trace append
to the trace's shared state under a lock; span *identity* (ids, parents)
is immutable after creation.

W3C ``traceparent`` (``00-<trace>-<span>-01``) is the wire form: emitted
on the OpenAI-compatible provider path and accepted by both HTTP servers,
so a trace crosses process boundaries intact (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import contextvars
import re
import threading
import time
import uuid
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator, Optional

__all__ = [
    "Span",
    "Trace",
    "Tracer",
    "annotate",
    "annotate_root",
    "current_span",
    "current_trace_id",
    "current_traceparent",
    "format_traceparent",
    "parse_traceparent",
    "span",
]

#: W3C trace-context header shape (version 00; future versions accepted
#: as long as the id fields parse)
_TRACEPARENT_RE = re.compile(
    r"^(?P<version>[0-9a-f]{2})-(?P<trace_id>[0-9a-f]{32})"
    r"-(?P<span_id>[0-9a-f]{16})-(?P<flags>[0-9a-f]{2})(?:-.*)?$"
)


def _new_trace_id() -> str:
    return uuid.uuid4().hex  # 16 random bytes = the W3C trace-id width


def _new_span_id() -> str:
    return uuid.uuid4().hex[:16]


def format_traceparent(trace_id: str, span_id: str) -> str:
    return f"00-{trace_id}-{span_id}-01"


def parse_traceparent(header: Optional[str]) -> Optional[tuple[str, str]]:
    """``(trace_id, parent_span_id)`` from a traceparent header, or None
    for anything malformed (all-zero ids are explicitly invalid per the
    spec — a buggy client must not join every request into one trace)."""
    if not header:
        return None
    match = _TRACEPARENT_RE.match(header.strip().lower())
    if match is None:
        return None
    if match.group("version") == "ff":
        return None
    trace_id, span_id = match.group("trace_id"), match.group("span_id")
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id


class _TraceState:
    """Shared mutable state of one in-flight trace: the finished-span
    list (appended from any task/thread under the lock) and the root
    span, reachable from every child via the ambient context."""

    __slots__ = ("trace_id", "root", "finished", "lock", "clock_ns")

    def __init__(self, trace_id: str, root: "Span", clock_ns: Callable[[], int]) -> None:
        self.trace_id = trace_id
        self.root = root
        self.finished: list["Span"] = []
        self.lock = threading.Lock()
        self.clock_ns = clock_ns

    def add(self, span_: "Span") -> None:
        with self.lock:
            self.finished.append(span_)


@dataclass
class Span:
    """One timed region of one trace.  ``start_ns``/``end_ns`` are on the
    tracer's monotonic clock — durations and in-trace ordering are exact;
    wall-clock anchoring lives on the enclosing trace record."""

    trace_id: str
    span_id: str
    parent_id: Optional[str]
    name: str
    start_ns: int
    end_ns: Optional[int] = None
    attributes: dict = field(default_factory=dict)
    status: str = "ok"  # "ok" | "error"
    error: Optional[str] = None
    #: trace bookkeeping, never serialized
    _state: Optional[_TraceState] = field(default=None, repr=False, compare=False)

    @property
    def duration_ms(self) -> float:
        end = self.end_ns if self.end_ns is not None else self.start_ns
        return (end - self.start_ns) / 1e6

    def set(self, **attributes: Any) -> "Span":
        self.attributes.update(attributes)
        return self

    def to_dict(self) -> dict:
        out: dict[str, Any] = {
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "name": self.name,
            "startNs": self.start_ns,
            "endNs": self.end_ns,
            "durationMs": round(self.duration_ms, 3),
            "status": self.status,
        }
        if self.parent_id:
            out["parentId"] = self.parent_id
        if self.attributes:
            out["attributes"] = dict(self.attributes)
        if self.error:
            out["error"] = self.error
        return out

    @classmethod
    def parse(cls, data: dict) -> "Span":
        return cls(
            trace_id=data.get("traceId", ""),
            span_id=data.get("spanId", ""),
            parent_id=data.get("parentId"),
            name=data.get("name", ""),
            start_ns=int(data.get("startNs", 0)),
            end_ns=(None if data.get("endNs") is None else int(data["endNs"])),
            attributes=dict(data.get("attributes") or {}),
            status=data.get("status", "ok"),
            error=data.get("error"),
        )


@dataclass
class Trace:
    """One completed analysis: the root span plus every finished child,
    sorted by start time."""

    trace_id: str
    name: str
    spans: list[Span] = field(default_factory=list)

    @property
    def root(self) -> Optional[Span]:
        for span_ in self.spans:
            if span_.parent_id is None:
                return span_
        return self.spans[0] if self.spans else None

    @property
    def duration_ms(self) -> float:
        root = self.root
        return root.duration_ms if root is not None else 0.0

    @property
    def status(self) -> str:
        root = self.root
        return root.status if root is not None else "ok"

    def children(self, span_id: str) -> list[Span]:
        return [s for s in self.spans if s.parent_id == span_id]

    def to_dict(self) -> dict:
        return {
            "traceId": self.trace_id,
            "name": self.name,
            "durationMs": round(self.duration_ms, 3),
            "status": self.status,
            "spans": [s.to_dict() for s in self.spans],
        }

    @classmethod
    def parse(cls, data: dict) -> "Trace":
        return cls(
            trace_id=data.get("traceId", ""),
            name=data.get("name", ""),
            spans=[Span.parse(s) for s in (data.get("spans") or [])],
        )


def stage_durations(root: "Span") -> dict:
    """Finished DIRECT children of ``root`` as ``{name: duration_ms}`` —
    the stage split the SLO ledger journals per request (one source of
    truth: the same spans the flight recorder stores).  A repeated stage
    name keeps its last finish; an out-of-trace root returns ``{}``."""
    state = root._state
    if state is None:
        return {}
    with state.lock:
        spans = list(state.finished)
    return {
        s.name: round(s.duration_ms, 3)
        for s in spans
        if s.parent_id == root.span_id
    }


#: the ambient current span (None outside any trace).  One ContextVar for
#: the whole process: traces are distinguished by the span's _state, not
#: by the variable, so concurrent tasks each see their own chain.
_CURRENT: contextvars.ContextVar[Optional[Span]] = contextvars.ContextVar(
    "operator_tpu_obs_span", default=None
)


def current_span() -> Optional[Span]:
    return _CURRENT.get()


def current_trace_id() -> Optional[str]:
    span_ = _CURRENT.get()
    return span_.trace_id if span_ is not None and span_._state is not None else None


def current_traceparent() -> Optional[str]:
    """The outbound W3C header for the ambient span (None outside a
    trace) — what the OpenAI-compat provider stamps on its HTTP calls."""
    span_ = _CURRENT.get()
    if span_ is None or span_._state is None:
        return None
    return format_traceparent(span_.trace_id, span_.span_id)


def annotate(**attributes: Any) -> None:
    """Attach attributes to the ambient span; no-op outside a trace."""
    span_ = _CURRENT.get()
    if span_ is not None:
        span_.attributes.update(attributes)


def annotate_root(key: str, value: Any, *, overwrite: bool = True) -> None:
    """Attach an attribute to the ambient trace's ROOT span — how deep
    code (a provider backend, the engine) flags a trace-level condition
    (``blackbox`` reasons) without plumbing the root around.  With
    ``overwrite=False`` the first writer wins — the first failure cause
    is the one the black-box dump reports."""
    span_ = _CURRENT.get()
    if span_ is None or span_._state is None:
        return
    root = span_._state.root
    if overwrite or key not in root.attributes:
        root.attributes[key] = value


@contextmanager
def span(name: str, **attributes: Any) -> Iterator[Span]:
    """A child span of the ambient span.

    Module-level (not a Tracer method) so deep layers — the serving
    engine, provider backends — can open spans without holding a tracer:
    the span joins whatever trace is ambient, and outside any trace it
    degrades to a detached, never-recorded timer (zero-cost observability
    for external completion-API callers that sent no traceparent).

    An exception propagating out marks the span ``status="error"`` and
    re-raises.
    """
    parent = _CURRENT.get()
    state = parent._state if parent is not None else None
    clock_ns = state.clock_ns if state is not None else time.monotonic_ns
    span_ = Span(
        trace_id=state.trace_id if state is not None else "",
        span_id=_new_span_id(),
        parent_id=parent.span_id if parent is not None else None,
        name=name,
        start_ns=clock_ns(),
        attributes=dict(attributes),
        _state=state,
    )
    token = _CURRENT.set(span_)
    try:
        yield span_
    except BaseException as exc:
        span_.status = "error"
        span_.error = span_.error or repr(exc)
        raise
    finally:
        _CURRENT.reset(token)
        span_.end_ns = clock_ns()
        if state is not None:
            state.add(span_)


class Tracer:
    """Starts traces and hands the completed :class:`Trace` to a flight
    recorder (``recorder.record(trace)``); ``recorder=None`` keeps
    everything in-flight-only (spans still time, nothing is retained).

    ``clock_ns`` is injectable so tests can shape span durations
    deterministically; child spans inherit the trace's clock.
    """

    def __init__(
        self,
        recorder: Optional[Any] = None,
        *,
        clock_ns: Optional[Callable[[], int]] = None,
    ) -> None:
        self.recorder = recorder
        self.clock_ns = clock_ns or time.monotonic_ns

    # spans delegate to the module-level ambient implementation, so a
    # mixed codebase (tracer-holding pipeline, tracer-free engine) builds
    # ONE tree per trace
    span = staticmethod(span)

    @contextmanager
    def trace(
        self,
        name: str,
        *,
        trace_id: Optional[str] = None,
        parent_id: Optional[str] = None,
        attributes: Optional[dict] = None,
    ) -> Iterator[Span]:
        """Open a new trace (root span).  ``trace_id``/``parent_id`` from
        a parsed inbound ``traceparent`` join the caller's distributed
        trace; otherwise a fresh id is minted.  On exit the assembled
        :class:`Trace` goes to the recorder; exceptions mark the root
        ``error`` and re-raise."""
        tid = trace_id or _new_trace_id()
        root = Span(
            trace_id=tid,
            span_id=_new_span_id(),
            parent_id=None,
            name=name,
            start_ns=self.clock_ns(),
            attributes=dict(attributes or {}),
        )
        state = _TraceState(tid, root, self.clock_ns)
        root._state = state
        #: a remote parent is metadata, not a local span — the local root
        #: stays the tree root and the link survives in the attributes
        if parent_id:
            root.attributes.setdefault("remote_parent", parent_id)
        token = _CURRENT.set(root)
        try:
            yield root
        except BaseException as exc:
            root.status = "error"
            root.error = root.error or repr(exc)
            raise
        finally:
            _CURRENT.reset(token)
            root.end_ns = self.clock_ns()
            with state.lock:
                spans = [root, *state.finished]
            spans.sort(key=lambda s: s.start_ns)
            completed = Trace(trace_id=tid, name=name, spans=spans)
            if self.recorder is not None:
                try:
                    self.recorder.record(completed)
                except Exception:  # noqa: BLE001 - tracing must never fail the traced work
                    import logging

                    logging.getLogger(__name__).warning(
                        "flight recorder rejected trace %s", tid, exc_info=True
                    )
