"""Program construction: every jitted XLA program the generator runs.

The compile layer split out of serving/engine.py (VERDICT r4 item 8): the
decode step/block variants (plain/paged x unguided/guided), the sampler,
the prefill-bucket factories (plain, paged, shared-prefix suffix), and the
chunked-prefill chunk/finish programs.  Pure construction — program CACHES
(_prefill_fns/_prefix_fns/_chunk_fns/_finish_fns) and all mutable state
stay on the generator; these methods close over `self` only for static
configuration (config, mesh, shardings, sampler knobs).

Mixed into :class:`serving.engine.BatchedGenerator`.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

from ..models.llama import KVCache, forward

log = logging.getLogger(__name__)


class ProgramBuilderMixin:
    """Builders for the generator's compiled programs (see module doc)."""

    #: unroll the K-step decode block into straight-line XLA instead of a
    #: lax.scan: a scan CARRIES the whole KV cache/page pool, and XLA's
    #: loop handling may double-buffer (copy) the carry every iteration —
    #: unrolled, updates chain without loop plumbing.  Experiment knob
    #: (scripts/tpu_experiments.sh); compile time grows ~K-fold.
    DECODE_UNROLL = os.environ.get("OPERATOR_TPU_DECODE_UNROLL", "0") == "1"

    #: nucleus-sampling candidate-set size (constructor: ``sample_top_k``).
    #: A full-vocab ``top_k`` is a 32k-128k element sort on the TPU vector
    #: units EVERY decode step, so sampling is truncated to the top-k
    #: candidates FIRST and the top-p cutoff computed within them — i.e.
    #: the served distribution is top-k AND top-p composed, the standard
    #: serving trade.  At this system's temperatures (0.3 default,
    #: aiprovider-crd.yaml:56-58) the top-64 hold ~all the nucleus mass; at
    #: temperatures ~1+ the truncation measurably narrows diversity vs true
    #: nucleus sampling — raise sample_top_k (e.g. 256) if that matters
    #: more than decode latency.
    SAMPLE_TOP_K = 64

    def _decode_step(self, params, cache, tokens, offsets, rng, temp, top_p, active,
                     lora=None, lora_idx=None,
                     gtables=None, gaut=None, gstate=None):
        """[B,1] tokens at per-slot offsets -> next token per slot."""
        jnp = self._jnp
        positions = offsets[:, None]
        logits, cache = forward(
            params, self.config, tokens, positions, cache=cache, cache_offset=offsets,
            lora=lora, lora_alpha=self.lora_alpha, lora_indices=lora_idx,
        )
        last = logits[:, -1, :]
        if gtables is not None:
            row = gtables[gaut, gstate]
            last = jnp.where(row >= 0, last, -jnp.inf)
        next_tokens, rng = self._sample(last, rng, temp, top_p)
        # inactive slots keep decoding garbage into their own slot space;
        # offsets only advance for active ones so their state is untouched
        offsets = jnp.where(active, offsets + 1, offsets)
        if gtables is None:
            return cache, next_tokens, offsets, rng
        stepped = jnp.take_along_axis(row, next_tokens[:, None], axis=1)[:, 0]
        gstate = jnp.where(active & (stepped >= 0), stepped, gstate)
        return cache, next_tokens, offsets, rng, gstate

    def _decode_step_paged(self, params, paged, tokens, rng, temp, top_p, active,
                           lora=None, lora_idx=None,
                           gtables=None, gaut=None, gstate=None):
        """Paged twin of :meth:`_decode_step` (released slots write to the
        trash page via their zeroed page-table row; their lengths stay put).
        With guided args, the sampler is masked by the automaton row and the
        per-slot DFA state advances — returned as an extra carry."""
        from ..models.llama import decode_step_paged
        from ..ops.paged_attention import PagedKVCache

        jnp = self._jnp
        logits, new_paged = decode_step_paged(
            params, self.config, tokens, paged,
            lora=lora, lora_alpha=self.lora_alpha, lora_indices=lora_idx,
        )
        if gtables is not None:
            row = gtables[gaut, gstate]  # [B, vocab] allowed-transition rows
            logits = jnp.where(row >= 0, logits, -jnp.inf)
        next_tokens, rng = self._sample(logits, rng, temp, top_p)
        lengths = jnp.where(active, new_paged.lengths, paged.lengths)
        new_paged = PagedKVCache(
            k_pages=new_paged.k_pages, v_pages=new_paged.v_pages,
            page_table=new_paged.page_table, lengths=lengths,
        )
        if gtables is None:
            return new_paged, next_tokens, rng
        stepped = jnp.take_along_axis(row, next_tokens[:, None], axis=1)[:, 0]
        gstate = jnp.where(active & (stepped >= 0), stepped, gstate)
        return new_paged, next_tokens, rng, gstate

    def _decode_block(self, params, cache, tokens, offsets, rng, temp, top_p, active,
                      lora=None, lora_idx=None):
        """K chained decode steps in one program; returns the [K, B] token
        matrix plus final carry state.  lax.scan by default, straight-line
        unrolled under OPERATOR_TPU_DECODE_UNROLL=1 (see DECODE_UNROLL)."""
        jax, jnp = self._jax, self._jnp

        if self.DECODE_UNROLL:
            toks = []
            for _ in range(self.decode_block):
                cache, next_tokens, offsets, rng = self._decode_step(
                    params, cache, tokens, offsets, rng, temp, top_p, active,
                    lora, lora_idx,
                )
                tokens = next_tokens[:, None]
                toks.append(next_tokens)
            return cache, jnp.stack(toks), tokens, offsets, rng

        def body(carry, _):
            cache, tokens, offsets, rng = carry
            cache, next_tokens, offsets, rng = self._decode_step(
                params, cache, tokens, offsets, rng, temp, top_p, active,
                lora, lora_idx,
            )
            return (cache, next_tokens[:, None], offsets, rng), next_tokens

        (cache, last, offsets, rng), toks = jax.lax.scan(
            body, (cache, tokens, offsets, rng), None, length=self.decode_block
        )
        return cache, toks, last, offsets, rng

    def _decode_block_paged(self, params, paged, tokens, rng, temp, top_p, active,
                            lora=None, lora_idx=None):
        jax, jnp = self._jax, self._jnp

        if self.DECODE_UNROLL:
            toks = []
            for _ in range(self.decode_block):
                paged, next_tokens, rng = self._decode_step_paged(
                    params, paged, tokens, rng, temp, top_p, active,
                    lora, lora_idx,
                )
                tokens = next_tokens[:, None]
                toks.append(next_tokens)
            return paged, jnp.stack(toks), tokens, rng

        def body(carry, _):
            paged, tokens, rng = carry
            paged, next_tokens, rng = self._decode_step_paged(
                params, paged, tokens, rng, temp, top_p, active,
                lora, lora_idx,
            )
            return (paged, next_tokens[:, None], rng), next_tokens

        (paged, last, rng), toks = jax.lax.scan(
            body, (paged, tokens, rng), None, length=self.decode_block
        )
        return paged, toks, last, rng

    def _decode_block_guided(self, params, cache, tokens, offsets, rng, temp,
                             top_p, active, lora, lora_idx,
                             gtables, gaut, gstate):
        """Guided twin of :meth:`_decode_block`: the DFA state joins the
        scan carry, so masking and stepping never leave the device."""
        jax, jnp = self._jax, self._jnp

        if self.DECODE_UNROLL:
            toks = []
            for _ in range(self.decode_block):
                cache, next_tokens, offsets, rng, gstate = self._decode_step(
                    params, cache, tokens, offsets, rng, temp, top_p, active,
                    lora, lora_idx, gtables, gaut, gstate,
                )
                tokens = next_tokens[:, None]
                toks.append(next_tokens)
            return cache, jnp.stack(toks), tokens, offsets, rng, gstate

        def body(carry, _):
            cache, tokens, offsets, rng, gstate = carry
            cache, next_tokens, offsets, rng, gstate = self._decode_step(
                params, cache, tokens, offsets, rng, temp, top_p, active,
                lora, lora_idx, gtables, gaut, gstate,
            )
            return (cache, next_tokens[:, None], offsets, rng, gstate), next_tokens

        (cache, last, offsets, rng, gstate), toks = jax.lax.scan(
            body, (cache, tokens, offsets, rng, gstate), None,
            length=self.decode_block,
        )
        return cache, toks, last, offsets, rng, gstate

    def _decode_block_paged_guided(self, params, paged, tokens, rng, temp,
                                   top_p, active, lora, lora_idx,
                                   gtables, gaut, gstate):
        jax, jnp = self._jax, self._jnp

        if self.DECODE_UNROLL:
            toks = []
            for _ in range(self.decode_block):
                paged, next_tokens, rng, gstate = self._decode_step_paged(
                    params, paged, tokens, rng, temp, top_p, active,
                    lora, lora_idx, gtables, gaut, gstate,
                )
                tokens = next_tokens[:, None]
                toks.append(next_tokens)
            return paged, jnp.stack(toks), tokens, rng, gstate

        def body(carry, _):
            paged, tokens, rng, gstate = carry
            paged, next_tokens, rng, gstate = self._decode_step_paged(
                params, paged, tokens, rng, temp, top_p, active,
                lora, lora_idx, gtables, gaut, gstate,
            )
            return (paged, next_tokens[:, None], rng, gstate), next_tokens

        (paged, last, rng, gstate), toks = jax.lax.scan(
            body, (paged, tokens, rng, gstate), None, length=self.decode_block
        )
        return paged, toks, last, rng, gstate

    def _get_guided_decode_fn(self):
        if self._decode_fn_guided is None:
            jax = self._jax
            body = (
                self._decode_block_paged_guided if self.paged
                else self._decode_block_guided
            )
            if self.mesh is None:
                self._decode_fn_guided = jax.jit(body, donate_argnums=(1,))
            else:
                # mirrors the unguided mesh programs: automaton tables
                # replicate (tens of MB, read-only), per-slot aut/state
                # shard over the data axes with the other [B] vectors
                from jax.sharding import NamedSharding, PartitionSpec as P

                s = self._shardings
                block_tokens = NamedSharding(self.mesh, P(None, ("dp", "fsdp")))
                if self.paged:
                    self._decode_fn_guided = jax.jit(
                        body,
                        in_shardings=(
                            self._param_shardings, s["paged"], s["tokens"],
                            s["repl"], s["batch"], s["batch"], s["batch"],
                            s["repl"], s["batch"],  # lora stack, idx
                            s["repl"], s["batch"], s["batch"],  # tables, aut, state
                        ),
                        out_shardings=(
                            s["paged"], block_tokens, s["tokens"], s["repl"],
                            s["batch"],
                        ),
                        donate_argnums=(1,),
                    )
                else:
                    self._decode_fn_guided = jax.jit(
                        body,
                        in_shardings=(
                            self._param_shardings, s["cache"], s["tokens"],
                            s["batch"], s["repl"], s["batch"], s["batch"],
                            s["batch"], s["repl"], s["batch"],
                            s["repl"], s["batch"], s["batch"],
                        ),
                        out_shardings=(
                            s["cache"], block_tokens, s["tokens"], s["batch"],
                            s["repl"], s["batch"],
                        ),
                        donate_argnums=(1,),
                    )
            self._decode_fn_guided = self._aot_wrap(
                "decode_guided", self._decode_fn_guided
            )
        return self._decode_fn_guided

    def _sample(self, logits, rng, temp, top_p):
        """Temperature + truncated-nucleus sampling; temp<=0 means greedy.

        [B, V] logits -> [B] token ids.  top-p filtering runs inside the
        top-``sample_top_k`` candidates (renormalised by categorical), not
        the full vocab — see SAMPLE_TOP_K above for the semantics trade.
        """
        jax, jnp = self._jax, self._jnp
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

        safe_temp = jnp.maximum(temp, 1e-4)[:, None]
        scaled = logits.astype(jnp.float32) / safe_temp
        k = min(self.sample_top_k, logits.shape[-1])
        top_logits, top_idx = jax.lax.top_k(scaled, k)
        probs = jax.nn.softmax(top_logits, axis=-1)
        cumulative = jnp.cumsum(probs, axis=-1) - probs  # exclusive prefix
        keep = cumulative < top_p[:, None]  # first token always kept
        filtered = jnp.where(keep, top_logits, -jnp.inf)
        rng, sub = jax.random.split(rng)
        choice = jax.random.categorical(sub, filtered, axis=-1)
        sampled = jnp.take_along_axis(top_idx, choice[:, None], axis=-1)[:, 0]
        picked = jnp.where(temp <= 0.0, greedy, sampled.astype(jnp.int32))
        return picked, rng

    def _prefill_shardings(self, n_pad: int):
        """(row, vec) shardings for a prefill bucket.  dp-aware admission
        (_admit_batch) always pads the bucket to a multiple of dp*fsdp, so
        rows shard over the data axes unconditionally."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        assert n_pad % self._dp_total == 0, (n_pad, self._dp_total)
        return (
            NamedSharding(self.mesh, P(("dp", "fsdp"), None)),
            NamedSharding(self.mesh, P(("dp", "fsdp"))),
        )

    def _prefill_score_shards(self) -> int:
        """Devices the prefill batch axis is sharded over — the
        chunked-attention budget is per-device (models/llama.py)."""
        return self._dp_total if self.mesh is not None else 1

    def _make_prefill(self, n_pad: int, t_pad: int, guided: bool = False):
        """Compile a prefill program for the (n_pad, t_pad) bucket."""
        jax, jnp = self._jax, self._jnp
        config = self.config
        score_shards = self._prefill_score_shards()

        def prefill_fn(params, cache, token_ids, lengths, slot_ids, rng, temp, top_p,
                       lora=None, lora_idx=None, gtables=None, gaut=None):
            # fresh contiguous mini-cache for the prompt tokens
            mini = KVCache.create(config, n_pad, t_pad, dtype=cache.k.dtype)
            positions = jnp.broadcast_to(
                jnp.arange(t_pad, dtype=jnp.int32)[None], (n_pad, t_pad)
            )
            kv_valid = positions < lengths[:, None]
            # kv_valid (not a materialised mask) so long buckets take the
            # chunked-prefill path in models/llama.py — no [T, S] f32 scores
            logits, mini = forward(
                params, config, token_ids, positions, cache=mini,
                cache_offset=0, kv_valid=kv_valid, score_shards=score_shards,
                prefill_lengths=lengths,
                lora=lora, lora_alpha=self.lora_alpha, lora_indices=lora_idx,
            )
            # scatter the prompt KV into the big cache rows for these slots
            # (slot axis is axis 1 of [L, B, S, KH, D])
            k = cache.k.at[:, slot_ids, :t_pad].set(mini.k.astype(cache.k.dtype))
            v = cache.v.at[:, slot_ids, :t_pad].set(mini.v.astype(cache.v.dtype))
            last = jnp.take_along_axis(
                logits, (lengths - 1)[:, None, None].astype(jnp.int32), axis=1
            )[:, 0, :]
            if guided:
                row = gtables[gaut, jnp.zeros_like(gaut)]  # DFA start state
                last = jnp.where(row >= 0, last, -jnp.inf)
            first_tokens, rng = self._sample(last, rng, temp, top_p)
            if guided:
                first_state = jnp.take_along_axis(
                    row, first_tokens[:, None], axis=1
                )[:, 0]
                return KVCache(k=k, v=v), first_tokens, rng, jnp.maximum(first_state, 0)
            return KVCache(k=k, v=v), first_tokens, rng

        if self.mesh is None:
            return jax.jit(prefill_fn)
        s = self._shardings
        rows, vec = self._prefill_shardings(n_pad)
        in_shardings = (
            self._param_shardings, s["cache"], rows, vec, vec,
            s["repl"], vec, vec, s["repl"], vec,
        )
        out_shardings = (s["cache"], vec, s["repl"])
        if guided:
            in_shardings += (s["repl"], vec)   # tables, row automaton ids
            out_shardings += (vec,)            # first DFA state per row
        return jax.jit(
            prefill_fn, in_shardings=in_shardings, out_shardings=out_shardings
        )

    def _make_prefill_paged(self, n_pad: int, t_pad: int, guided: bool = False):
        """Prefill for the paged cache: same mini-cache forward, then the
        prompt KV scatters into each sequence's pages (write_tokens with
        valid_len so padded rows land in the trash page)."""
        jax, jnp = self._jax, self._jnp
        config = self.config
        score_shards = self._prefill_score_shards()

        def prefill_fn(params, paged, token_ids, lengths, row_tables, rng, temp, top_p,
                       lora=None, lora_idx=None, gtables=None, gaut=None):
            from ..ops.paged_attention import PagedKVCache, write_tokens

            mini = KVCache.create(config, n_pad, t_pad, dtype=paged.k_pages.dtype)
            positions = jnp.broadcast_to(
                jnp.arange(t_pad, dtype=jnp.int32)[None], (n_pad, t_pad)
            )
            kv_valid = positions < lengths[:, None]
            logits, mini = forward(
                params, config, token_ids, positions, cache=mini,
                cache_offset=0, kv_valid=kv_valid, score_shards=score_shards,
                prefill_lengths=lengths,
                lora=lora, lora_alpha=self.lora_alpha, lora_indices=lora_idx,
            )
            zero = jnp.zeros((n_pad,), jnp.int32)
            scatter = jax.vmap(write_tokens, in_axes=(0, None, 0, None, None))
            k_pages = scatter(paged.k_pages, row_tables, mini.k, zero, lengths)
            v_pages = scatter(paged.v_pages, row_tables, mini.v, zero, lengths)
            last = jnp.take_along_axis(
                logits, (lengths - 1)[:, None, None].astype(jnp.int32), axis=1
            )[:, 0, :]
            if guided:
                row = gtables[gaut, jnp.zeros_like(gaut)]  # DFA start state
                last = jnp.where(row >= 0, last, -jnp.inf)
            first_tokens, rng = self._sample(last, rng, temp, top_p)
            new_paged = PagedKVCache(
                k_pages=k_pages, v_pages=v_pages,
                page_table=paged.page_table, lengths=paged.lengths,
            )
            if guided:
                first_state = jnp.take_along_axis(
                    row, first_tokens[:, None], axis=1
                )[:, 0]
                return new_paged, first_tokens, rng, jnp.maximum(first_state, 0)
            return new_paged, first_tokens, rng

        if self.mesh is None:
            return jax.jit(prefill_fn)
        s = self._shardings
        rows, vec = self._prefill_shardings(n_pad)
        in_shardings = (
            self._param_shardings, s["paged"], rows, vec, rows,
            s["repl"], vec, vec, s["repl"], vec,
        )
        out_shardings = (s["paged"], vec, s["repl"])
        if guided:
            in_shardings += (s["repl"], vec)
            out_shardings += (vec,)
        return jax.jit(
            prefill_fn, in_shardings=in_shardings, out_shardings=out_shardings
        )

    def _make_prefill_paged_prefixed(
        self, n_pad: int, t_sfx: int, shared: int, guided: bool = False
    ):
        """Suffix-only prefill: the first ``shared`` tokens' KV is gathered
        from the cached prefix pages into the mini cache (read-only reuse),
        and only ``t_sfx`` suffix tokens run through the model."""
        jax, jnp = self._jax, self._jnp
        config = self.config
        score_shards = self._prefill_score_shards()
        n_prefix_pages = shared // self.page_size
        t_total = shared + t_sfx

        def prefill_fn(params, paged, prefix_table, token_ids, lengths,
                       row_tables, rng, temp, top_p,
                       lora=None, lora_idx=None, gtables=None, gaut=None):
            from ..ops.paged_attention import PagedKVCache, write_tokens

            # prefix KV: pages -> contiguous [L, shared, KH, D], shared by
            # every row of the mini cache (broadcast, not per-row copies)
            def gather(pages):
                picked = pages[:, prefix_table]  # [L, n_pp, ps, KH, D]
                return picked.reshape(
                    pages.shape[0], shared, *pages.shape[3:]
                )

            mini = KVCache.create(config, n_pad, t_total, dtype=paged.k_pages.dtype)
            mini = KVCache(
                k=mini.k.at[:, :, :shared].set(
                    gather(paged.k_pages).astype(mini.k.dtype)[:, None]
                ),
                v=mini.v.at[:, :, :shared].set(
                    gather(paged.v_pages).astype(mini.v.dtype)[:, None]
                ),
            )
            positions = shared + jnp.broadcast_to(
                jnp.arange(t_sfx, dtype=jnp.int32)[None], (n_pad, t_sfx)
            )
            kv_positions = jnp.broadcast_to(
                jnp.arange(t_total, dtype=jnp.int32)[None], (n_pad, t_total)
            )
            kv_valid = kv_positions < lengths[:, None]
            logits, mini = forward(
                params, config, token_ids, positions, cache=mini,
                cache_offset=jnp.full((n_pad,), shared, jnp.int32),
                kv_valid=kv_valid, score_shards=score_shards,
                lora=lora, lora_alpha=self.lora_alpha, lora_indices=lora_idx,
            )
            # scatter ONLY the suffix into this wave's own pages — the
            # prefix pages are shared and must never be rewritten
            start = jnp.full((n_pad,), shared, jnp.int32)
            suffix_len = lengths - shared
            suffix_k = jax.lax.slice_in_dim(mini.k, shared, t_total, axis=2)
            suffix_v = jax.lax.slice_in_dim(mini.v, shared, t_total, axis=2)
            zero_scatter = jax.vmap(write_tokens, in_axes=(0, None, 0, None, None))
            k_pages = zero_scatter(paged.k_pages, row_tables, suffix_k, start, suffix_len)
            v_pages = zero_scatter(paged.v_pages, row_tables, suffix_v, start, suffix_len)
            last = jnp.take_along_axis(
                logits, (lengths - 1 - shared)[:, None, None].astype(jnp.int32),
                axis=1,
            )[:, 0, :]
            if guided:
                row = gtables[gaut, jnp.zeros_like(gaut)]
                last = jnp.where(row >= 0, last, -jnp.inf)
            first_tokens, rng = self._sample(last, rng, temp, top_p)
            new_paged = PagedKVCache(
                k_pages=k_pages, v_pages=v_pages,
                page_table=paged.page_table, lengths=paged.lengths,
            )
            if guided:
                first_state = jnp.take_along_axis(
                    row, first_tokens[:, None], axis=1
                )[:, 0]
                return new_paged, first_tokens, rng, jnp.maximum(first_state, 0)
            return new_paged, first_tokens, rng

        if self.mesh is None:
            return jax.jit(prefill_fn)
        s = self._shardings
        rows, vec = self._prefill_shardings(n_pad)
        in_shardings = (
            self._param_shardings, s["paged"], s["repl"], rows, vec, rows,
            s["repl"], vec, vec, s["repl"], vec,
        )
        out_shardings = (s["paged"], vec, s["repl"])
        if guided:
            in_shardings += (s["repl"], vec)
            out_shardings += (vec,)
        return jax.jit(
            prefill_fn, in_shardings=in_shardings, out_shardings=out_shardings
        )

    def _make_chunk_fn(self, n_pad: int, t_pad: int, chunk: int):
        """One prefill chunk: forward ``chunk`` tokens at a dynamic offset
        into the job's mini cache, carrying last-token logits for rows whose
        prompt ends inside this chunk."""
        jax, jnp = self._jax, self._jnp
        config = self.config
        score_shards = self._prefill_score_shards()

        def chunk_fn(params, mini, ids_chunk, lengths, offset, last_logits,
                     lora=None, lora_idx=None):
            positions = offset + jnp.broadcast_to(
                jnp.arange(chunk, dtype=jnp.int32)[None], (n_pad, chunk)
            )
            kv_positions = jnp.broadcast_to(
                jnp.arange(t_pad, dtype=jnp.int32)[None], (n_pad, t_pad)
            )
            # valid cache slots: written so far (incl. this chunk) AND real
            kv_valid = kv_positions < jnp.minimum(lengths, offset + chunk)[:, None]
            logits, mini = forward(
                params, config, ids_chunk, positions, cache=mini,
                cache_offset=jnp.broadcast_to(offset, (n_pad,)),
                kv_valid=kv_valid, score_shards=score_shards,
                lora=lora, lora_alpha=self.lora_alpha, lora_indices=lora_idx,
            )
            rel = lengths - 1 - offset  # last-token position, chunk-relative
            in_chunk = (rel >= 0) & (rel < chunk)
            gathered = jnp.take_along_axis(
                logits, jnp.clip(rel, 0, chunk - 1)[:, None, None].astype(jnp.int32),
                axis=1,
            )[:, 0, :]
            last_logits = jnp.where(in_chunk[:, None], gathered, last_logits)
            return mini, last_logits

        if self.mesh is None:
            return jax.jit(chunk_fn)
        # mesh: same layout as the one-shot prefill programs — rows shard
        # over the data axes (dp-aware admission pads the bucket), the
        # mini cache shards like the big cache (batch over dp, heads over
        # tp), and the chunk offset is a replicated scalar
        s = self._shardings
        rows, vec = self._prefill_shardings(n_pad)
        return jax.jit(
            chunk_fn,
            in_shardings=(
                self._param_shardings, s["cache"], rows, vec,
                s["repl"], rows, s["repl"], vec,
            ),
            out_shardings=(s["cache"], rows),
        )

    def _make_finish_fn(self, n_pad: int, t_pad: int, guided: bool = False):
        """Scatter the completed mini cache into the big cache / pages and
        sample each row's first token from the carried last logits (masked
        by the automaton start-state rows for guided waves)."""
        jax, jnp = self._jax, self._jnp

        def sample_first(last_logits, rng, temp, top_p, gtables, gaut):
            if guided:
                row = gtables[gaut, jnp.zeros_like(gaut)]
                last_logits = jnp.where(row >= 0, last_logits, -jnp.inf)
            first_tokens, rng = self._sample(last_logits, rng, temp, top_p)
            if guided:
                first_state = jnp.take_along_axis(
                    row, first_tokens[:, None], axis=1
                )[:, 0]
                return first_tokens, rng, (jnp.maximum(first_state, 0),)
            return first_tokens, rng, ()

        if self.paged:
            def finish_fn(paged, mini, lengths, row_tables, last_logits,
                          rng, temp, top_p, gtables=None, gaut=None):
                from ..ops.paged_attention import PagedKVCache, write_tokens

                zero = jnp.zeros((n_pad,), jnp.int32)
                scatter = jax.vmap(write_tokens, in_axes=(0, None, 0, None, None))
                k_pages = scatter(paged.k_pages, row_tables, mini.k, zero, lengths)
                v_pages = scatter(paged.v_pages, row_tables, mini.v, zero, lengths)
                first_tokens, rng, extra = sample_first(
                    last_logits, rng, temp, top_p, gtables, gaut
                )
                return (
                    PagedKVCache(
                        k_pages=k_pages, v_pages=v_pages,
                        page_table=paged.page_table, lengths=paged.lengths,
                    ),
                    first_tokens, rng, *extra,
                )
        else:
            def finish_fn(cache, mini, lengths, slot_ids, last_logits,
                          rng, temp, top_p, gtables=None, gaut=None):
                k = cache.k.at[:, slot_ids, :t_pad].set(mini.k.astype(cache.k.dtype))
                v = cache.v.at[:, slot_ids, :t_pad].set(mini.v.astype(cache.v.dtype))
                first_tokens, rng, extra = sample_first(
                    last_logits, rng, temp, top_p, gtables, gaut
                )
                return KVCache(k=k, v=v), first_tokens, rng, *extra

        if self.mesh is None:
            return jax.jit(finish_fn)
        s = self._shardings
        rows, vec = self._prefill_shardings(n_pad)
        if self.paged:
            # (paged, mini, lengths, row_tables, last_logits, rng, temp, top_p)
            in_shardings = (
                s["paged"], s["cache"], vec, rows, rows,
                s["repl"], vec, vec,
            )
            out_shardings = (s["paged"], vec, s["repl"])
        else:
            # (cache, mini, lengths, slot_ids, last_logits, rng, temp, top_p)
            in_shardings = (
                s["cache"], s["cache"], vec, vec, rows,
                s["repl"], vec, vec,
            )
            out_shardings = (s["cache"], vec, s["repl"])
        if guided:
            in_shardings += (s["repl"], vec)
            out_shardings += (vec,)
        return jax.jit(
            finish_fn, in_shardings=in_shardings, out_shardings=out_shardings
        )
