"""Block-hash prefix KV cache for the continuous scheduler.

The wave engine's registered-shared-prefix (engine.set_shared_prefix)
needs the prefix declared up front and serves only wave mode.  This
module generalizes it to *automatic* page-granular prefix caching
(vLLM-style APC) for the mixed prefill+decode program:

- A **block** is exactly one KV page of tokens (``page_size``).  Blocks
  are keyed by a rolling hash: ``h_i = sha256(h_{i-1} || tokens_i)``,
  so a block's identity pins its entire prefix — two requests share a
  block only when every token before it matches too.
- On admission the scheduler matches the request's longest cached block
  chain and maps those device pages into the row's page table
  **read-only** (refcounted); only the uncached suffix is prefilled.
  The ragged mixed program already handles arbitrary per-row q_count,
  so a hit is just a shorter prefill chunk.
- The match is capped at ``(len(tokens) - 1) // page_size`` blocks so at
  least one suffix token always prefills.  That makes the copy-on-write
  rule structural: a row's own writes (suffix prefill + generation)
  always start at ``cached_len`` — the first position of a row-owned
  page — so no row ever appends into a shared page and no copy is ever
  needed.  (A page-unaligned shared tail would require CoW; we simply
  never map one.)
- Eviction is LRU over refcount-zero blocks.  An evicted block may
  spill to the host pool (ops/kv_transfer.py) and be revived on the
  next hit — restore is one page DMA + a table write, not recompute.

KV vectors are per-token projections (W_k·x_t with absolute RoPE
positions), independent of how the prompt was chunked, so reusing a
cached page is numerically exact and greedy output stays byte-identical
cache-on vs cache-off.
"""

from __future__ import annotations

import hashlib
import threading
from dataclasses import dataclass, field
from typing import Optional, Sequence


def block_hashes(tokens: Sequence[int], page_size: int) -> list[bytes]:
    """Rolling hash chain over page-aligned token blocks.

    Returns one digest per FULL block (``len(tokens) // page_size``);
    the page-unaligned tail never gets a hash, so it can never be
    shared.  Digest i commits to tokens[0 : (i+1)*page_size].
    """
    out: list[bytes] = []
    h = b""
    for i in range(len(tokens) // page_size):
        block = tokens[i * page_size : (i + 1) * page_size]
        m = hashlib.sha256()
        m.update(h)
        m.update(b",".join(str(t).encode() for t in block))
        h = m.digest()[:16]
        out.append(h)
    return out


@dataclass
class CachedBlock:
    """One page-sized KV block owned by the store.

    ``page`` is the device page id holding the block's KV, or -1 when
    the block lives only in the host pool (evicted from device but
    restorable).  ``refs`` counts live rows currently reading the page;
    only refcount-zero device blocks are evictable.
    """

    hash: bytes
    parent: Optional[bytes]
    tokens: tuple
    page: int = -1
    refs: int = 0
    last_used: int = 0


class PrefixKVStore:
    """Refcounted page-granular prefix cache + LRU eviction policy.

    The store OWNS the device pages of its blocks (they are allocated
    from the same PageAllocator as row grants but tracked here, not in
    any row).  Rows acquire/release references; the scheduler drives
    insert (ownership transfer at prefill completion), eviction
    (``evict_lru`` when admission needs pages), and host offload.

    Threading: the scheduler mutates the store on the engine's
    single-thread decode executor, while the event loop reads it
    (``inventory``/``stats`` behind /healthz) and the fabric prefetch
    path probes/adopts into it.  Every method therefore takes one
    re-entrant lock so no reader ever iterates ``_blocks`` mid-mutation.
    The lock guards PER-METHOD invariants only — compound sequences
    (check residency, then forget; evict_lru, then mark_offloaded) stay
    correct because every MUTATING caller runs on the decode executor
    (the fabric fetcher ships its probe/adopt work there too).
    """

    def __init__(self, page_size: int, *, host_pool=None, metrics=None) -> None:
        self.page_size = page_size
        self.host_pool = host_pool  # ops/kv_transfer.HostKVPool or None
        self.metrics = metrics
        self._lock = threading.RLock()
        self._blocks: dict[bytes, CachedBlock] = {}
        #: hashes gathered off-device at eviction but not yet fetched
        #: into the host pool (the scheduler's _pending_offload holds the
        #: device buffers): restorable, just not via host_pool.get yet
        self.pending_offload: set[bytes] = set()
        self._clock = 0  # LRU tick, bumped on every match/acquire
        # cumulative lookup accounting (feeds prefixHitRate in /healthz)
        self.lookups = 0
        self.hits = 0
        self.misses = 0

    # -- introspection ----------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._blocks)

    def get(self, h: bytes) -> Optional[CachedBlock]:
        with self._lock:
            return self._blocks.get(h)

    @property
    def device_pages_held(self) -> int:
        """Device pages the store currently owns (resident blocks)."""
        with self._lock:
            return sum(1 for b in self._blocks.values() if b.page >= 0)

    def restorable(self, h: bytes) -> bool:
        """An off-device block that can come back without recompute:
        pooled on host, or gathered and awaiting the offload drain."""
        with self._lock:
            if h in self.pending_offload:
                return True
        return bool(self.host_pool and self.host_pool.has(h))

    def hit_rate(self) -> Optional[float]:
        total = self.hits + self.misses
        return (self.hits / total) if total else None

    def inventory(self, limit: int = 128) -> list[str]:
        """Most-recently-used block hashes (hex), for the /healthz peer
        index — bounded so the load report stays small."""
        with self._lock:
            blocks = sorted(
                self._blocks.values(), key=lambda b: b.last_used, reverse=True
            )
            return [b.hash.hex() for b in blocks[:limit]]

    def stats(self) -> dict:
        with self._lock:
            return {
                "blocks": len(self._blocks),
                "device_pages": self.device_pages_held,
                "host_blocks": (len(self.host_pool) if self.host_pool else 0),
                "lookups": self.lookups,
                "hits": self.hits,
                "misses": self.misses,
                "hit_rate": self.hit_rate(),
            }

    # -- matching ---------------------------------------------------------

    def match(self, tokens: Sequence[int]) -> list[CachedBlock]:
        """Longest cached chain of full blocks prefixing ``tokens``.

        Capped at ``(len(tokens) - 1) // page_size`` blocks so at least
        one token is always left for the row to prefill (the structural
        no-CoW rule — see module docstring).  A block counts as cached
        when it is device-resident OR restorable from the host pool.
        Updates hit/miss accounting at block granularity.
        """
        with self._lock:
            self._clock += 1
            self.lookups += 1
            ps = self.page_size
            matchable = max(0, (len(tokens) - 1) // ps)
            chain: list[CachedBlock] = []
            h = b""
            for i in range(matchable):
                block = tokens[i * ps : (i + 1) * ps]
                m = hashlib.sha256()
                m.update(h)
                m.update(b",".join(str(t).encode() for t in block))
                h = m.digest()[:16]
                entry = self._blocks.get(h)
                if entry is None:
                    break
                if entry.page < 0 and not self.restorable(h):
                    # stale index entry: neither on device nor restorable
                    break
                entry.last_used = self._clock
                chain.append(entry)
            self.hits += len(chain)
            self.misses += matchable - len(chain)
        if self.metrics is not None:
            if chain:
                self.metrics.incr("kv_hit", len(chain))
            if matchable - len(chain):
                self.metrics.incr("kv_miss", matchable - len(chain))
        return chain

    def probe(self, tokens: Sequence[int]) -> list[tuple[bytes, bool]]:
        """Pure residency probe over the matchable prefix — ``(hash,
        resident)`` per full block, no accounting, no LRU bump.

        The fabric prefetch path uses this to find which prefix blocks
        are worth fetching from a peer before admission runs the real
        ``match``.  ``resident`` means the block would count as cached:
        device-resident or restorable from the host side.
        """
        ps = self.page_size
        matchable = max(0, (len(tokens) - 1) // ps)
        out: list[tuple[bytes, bool]] = []
        with self._lock:
            for h in block_hashes(tokens[: matchable * ps], ps):
                entry = self._blocks.get(h)
                resident = entry is not None and (
                    entry.page >= 0 or self.restorable(h)
                )
                out.append((h, resident))
        return out

    # -- refcounts --------------------------------------------------------

    def acquire(self, blocks: Sequence[CachedBlock]) -> None:
        with self._lock:
            self._clock += 1
            for b in blocks:
                b.refs += 1
                b.last_used = self._clock

    def release(self, hashes: Sequence[bytes]) -> None:
        with self._lock:
            for h in hashes:
                entry = self._blocks.get(h)
                if entry is not None and entry.refs > 0:
                    entry.refs -= 1

    # -- insert / evict ---------------------------------------------------

    def insert(
        self,
        h: bytes,
        parent: Optional[bytes],
        tokens: Sequence[int],
        page: int,
        *,
        refs: int = 0,
    ) -> CachedBlock:
        """Register a block, transferring ownership of ``page`` to the
        store.  If the block already exists without a device page (host
        resident after eviction), the page is adopted — a free revival.
        """
        with self._lock:
            self._clock += 1
            entry = self._blocks.get(h)
            if entry is not None:
                if entry.page < 0 and page >= 0:
                    entry.page = page
                    entry.refs += refs
                    entry.last_used = self._clock
                    return entry
                # caller keeps its duplicate page; store already has one
                raise ValueError("block already device-resident")
            entry = CachedBlock(
                hash=h,
                parent=parent,
                tokens=tuple(tokens),
                page=page,
                refs=refs,
                last_used=self._clock,
            )
            self._blocks[h] = entry
            return entry

    def adopt_host(
        self, h: bytes, parent: Optional[bytes], tokens: Sequence[int]
    ) -> CachedBlock:
        """Register a host-pool-resident block fetched over the fabric.

        Unlike :meth:`insert` there is no device page to transfer — the
        entry lands restorable (``page = -1``) and the ordinary one-DMA
        restore path revives it when a match acquires it.  Idempotent:
        an existing entry (any residency) is returned untouched.
        """
        with self._lock:
            entry = self._blocks.get(h)
            if entry is not None:
                return entry
            self._clock += 1
            entry = CachedBlock(
                hash=h,
                parent=parent,
                tokens=tuple(tokens),
                page=-1,
                refs=0,
                last_used=self._clock,
            )
            self._blocks[h] = entry
            return entry

    def evictable(self) -> list[CachedBlock]:
        """Device-resident refcount-zero blocks, LRU first."""
        with self._lock:
            out = [
                b for b in self._blocks.values()
                if b.refs == 0 and b.page >= 0
            ]
        out.sort(key=lambda b: b.last_used)
        return out

    def evict_lru(self, count: int) -> list[CachedBlock]:
        """Pick up to ``count`` LRU refcount-zero device blocks for
        eviction.  Pure selection — the CALLER must, per block, gather
        the page's KV for host offload (or decide not to), return the
        page to the allocator, then call ``mark_offloaded`` (host copy
        exists/will exist) or ``forget`` (block is gone for good)."""
        victims = self.evictable()[:count]
        if victims and self.metrics is not None:
            self.metrics.incr("kv_evict", len(victims))
        return victims

    def mark_offloaded(self, h: bytes) -> None:
        """Block left the device but survives in the host pool: keep the
        index entry restorable (page = -1)."""
        with self._lock:
            entry = self._blocks.get(h)
            if entry is not None:
                entry.page = -1

    def forget(self, h: bytes) -> None:
        """Drop a block from the index entirely (evicted with no host
        copy — it can never be restored, so a match must miss)."""
        with self._lock:
            self._blocks.pop(h, None)

    def reset(self) -> None:
        """Device reset: every device page is gone (the generator
        rebuilds its allocator), but host-pool copies survive and their
        index entries stay restorable."""
        with self._lock:
            self.pending_offload.clear()  # gathered device buffers died
            for h in list(self._blocks):
                b = self._blocks[h]
                b.page = -1
                b.refs = 0
                if not (self.host_pool and self.host_pool.has(h)):
                    del self._blocks[h]
