"""Persisted AOT executables — the warm-start path for the serving engine.

BENCH_r02 (the one real-TPU run) spent ~27 s in warmup compile before the
first token; the supervisor's device-reset recovery and any scale-from-zero
autoscaler pay that again on every boot.  XLA's persistent *compilation*
cache (utils/platform.py) only skips the backend compile — tracing,
lowering and executable construction still run per program, and the cache
key is XLA's, not ours.  This module persists the **compiled executables
themselves** (``jax.experimental.serialize_executable``): on a warm boot
every serving program the grid drives is deserialized from disk instead of
compiled, so bring-up is dominated by the HBM weight transfer the loader
overlaps with it (models/loader.py ``load_params_async``).

Key discipline: executables are only valid for the exact (program shapes x
sharding x runtime) they were compiled for, so the cache directory is keyed
by a fingerprint over everything that shapes a program — model config,
engine shape grid inputs (slots/seq/paging/decode block/chunking), mesh
axes and device kind, weight/cache dtypes, jax+jaxlib versions and the
backend's platform version (libtpu on TPU).  Any mismatch is a MISS, never
a wrong load; any deserialize or call-time error falls back loudly to the
existing live compile (``CachedProgram``).

Wiring: ``BatchedGenerator`` owns an :class:`AotCache` when built with
``aot_cache_path`` (or a provider-prebuilt cache) and routes every program
construction site through ``_aot_wrap`` — wave prefill/chunk/finish/prefix
programs, both decode blocks, and the continuous scheduler's ONE mixed
program.  The supervisor's restart path needs no extra wiring: a reset
rebuilds programs through the same sites, which restore from the cache.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import pickle
import tempfile
import time
from typing import Any, Iterable, Optional

log = logging.getLogger(__name__)

#: bump when the on-disk record layout changes; old files then read as
#: corrupt (loud fallback + removal) instead of deserializing garbage
CACHE_FORMAT = 1

#: filename suffix for one serialized executable
_SUFFIX = ".aotx"


def _fresh_compile_scope():
    """Scope that bypasses XLA's persistent compilation cache for a compile
    whose executable will be serialized.  An executable reconstructed from
    a persistent-cache HIT serializes WITHOUT its jitted symbol definitions
    — ``deserialize_and_load`` then fails with "Symbols not found" in the
    next process, poisoning the stored ``.aotx``.  A fresh build serializes
    completely; nothing is lost because this cache supersedes XLA's for
    serving programs."""
    try:
        from jax._src import config as _jax_config

        return _jax_config.enable_compilation_cache(False)
    except Exception:  # noqa: BLE001 - private API moved: compile normally
        import contextlib

        return contextlib.nullcontext()

#: jit-ed function names of the serving programs (programs.py inner defs,
#: engine decode methods, sched/mixed.py) — what a compile-log event must
#: contain to count as a SERVING-program compile.  Host glue (eager
#: ``convert_element_type`` / ``scatter`` / ... mini-programs) recompiles
#: per process and is excluded: it is milliseconds, not the warmup grid.
SERVING_PROGRAM_MARKERS = (
    "prefill_fn", "chunk_fn", "finish_fn", "mixed_fn", "_decode_block",
)


def serving_compile_events(events: Iterable) -> list:
    """Filter a ``CompileWatcher`` event list down to serving-program
    compiles (see SERVING_PROGRAM_MARKERS).  Events are the watcher's
    ``(t, name, duration)`` tuples."""
    return [
        ev for ev in events
        if any(marker in ev[1] for marker in SERVING_PROGRAM_MARKERS)
    ]


def runtime_versions() -> dict:
    """The runtime facts an executable is only valid for: jax/jaxlib
    versions and the backend platform + its runtime version (libtpu on
    TPU).  ``AOT_CACHE_SALT`` folds in so operators (and tests) can force
    a cold boot without deleting anything."""
    import jax

    try:
        import jaxlib

        jaxlib_version = getattr(jaxlib, "__version__", "?")
    except Exception:  # noqa: BLE001 - jaxlib is implied by jax, but stay safe
        jaxlib_version = "?"
    try:
        backend = jax.extend.backend.get_backend()
        platform = backend.platform
        platform_version = str(getattr(backend, "platform_version", ""))
    except Exception:  # noqa: BLE001 - no backend yet: fingerprint still works
        platform, platform_version = "uninitialised", ""
    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib_version,
        "platform": platform,
        "platform_version": platform_version,
        "salt": os.environ.get("AOT_CACHE_SALT", ""),
    }


def _dtype_name(dtype: Any) -> str:
    if dtype is None:
        return "bfloat16"
    return getattr(dtype, "__name__", None) or str(dtype)


def generator_fingerprint(
    *,
    config: Any,
    weight_dtype: str,
    max_slots: int,
    max_seq: Optional[int] = None,
    cache_dtype: Any = None,
    paged: bool = False,
    page_size: int = 64,
    kv_pages: Optional[int] = None,
    mesh: Any = None,
    decode_block: int = 1,
    sample_top_k: Optional[int] = None,
    pipeline_depth: int = 1,
    prefill_chunk: Optional[int] = None,
    sched_pipeline_depth: int = 1,
    spec_width: int = 1,
    kv_prefix_cache: bool = False,
    lora_names: Iterable[str] = (),
) -> dict:
    """The fingerprint payload for a ``BatchedGenerator`` shape.

    Called with the generator's constructor arguments (provider and tests)
    or its resolved attributes (the generator itself); light normalisation
    here keeps the two call sites agreeing.  A divergence is SAFE — it
    reads as a cache miss and the programs compile live."""
    try:
        model = dataclasses.asdict(config)
    except TypeError:
        model = {k: v for k, v in vars(config).items() if not k.startswith("_")}
    mesh_desc = None
    if mesh is not None:
        first = next(iter(mesh.devices.flat))
        mesh_desc = {
            "axes": dict(zip(mesh.axis_names, [int(s) for s in mesh.devices.shape])),
            "devices": int(mesh.devices.size),
            "kind": str(getattr(first, "device_kind", "?")),
        }
    max_seq_limit = int(model.get("max_seq_len") or 0) or None
    resolved_seq = min(max_seq or max_seq_limit, max_seq_limit) if max_seq_limit else max_seq
    return {
        "format": CACHE_FORMAT,
        "model": model,
        "weight_dtype": weight_dtype,
        "max_slots": int(max_slots),
        "max_seq": resolved_seq,
        "cache_dtype": _dtype_name(cache_dtype),
        "paged": bool(paged),
        "page_size": int(page_size),
        "kv_pages": int(kv_pages or 0),
        "mesh": mesh_desc,
        "decode_block": int(decode_block),
        "sample_top_k": int(sample_top_k) if sample_top_k else None,
        "pipeline_depth": int(pipeline_depth),
        "prefill_chunk": int(prefill_chunk) if prefill_chunk else None,
        # continuous-scheduler shape knobs: the mixed program's sampled
        # width (1 + spec_lookup_k) changes the compiled executable, and
        # depth keys the persisted-executable join even though the trace
        # is depth-independent (conservative: a depth flip re-warms)
        "sched_pipeline_depth": int(sched_pipeline_depth),
        "spec_width": int(spec_width),
        # prefix caching shapes the mixed program's page-table bounds
        # (cache-owned pages share the row tables): keying on it keeps a
        # cache-on executable from being replayed into a cache-off boot
        "kv_prefix_cache": bool(kv_prefix_cache),
        "lora": sorted(str(n) for n in lora_names if n),
        "runtime": runtime_versions(),
    }


def fingerprint_digest(payload: dict) -> str:
    blob = json.dumps(payload, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()


class AotCache:
    """One fingerprint-keyed directory of serialized serving executables.

    ``get``/``put`` never raise: a miss or any I/O / deserialize error
    degrades to live compilation with a loud log line and the
    ``podmortem_aot_cache_{hit,miss,store,error}_total`` counters, so a
    wrong cache can cost seconds, never correctness.
    """

    def __init__(self, path: str, payload: dict, *, metrics: Any = None) -> None:
        self.payload = payload
        self.fingerprint = fingerprint_digest(payload)
        self.dir = os.path.join(path, self.fingerprint[:32])
        self.metrics = metrics
        self.hits = 0
        self.misses = 0
        self.errors = 0
        self.stored = 0
        #: programs compiled LIVE under this cache (cold or fallback) —
        #: the number a warm-boot assertion wants to see at zero
        self.live_compiles = 0
        #: entries discarded for the KNOWN environmental failure: XLA
        #: raising "Symbols not found" at deserialize_and_load.  It means
        #: the stored executable was serialized from an XLA
        #: persistent-compilation-cache HIT — the runtime handed back a
        #: cached binary whose jitted symbol definitions were never
        #: embedded in the serialized payload, so the .aotx is poisoned
        #: at STORE time and only detectable at the next boot's load.
        #: Distinct from ``errors`` so warm-boot tests can tell "cache
        #: fell back for the documented environmental reason" apart from
        #: genuine corruption.
        self.symbol_errors = 0
        self._preloaded: dict[str, Any] = {}
        self._warned_cold = False

    # -- bookkeeping ----------------------------------------------------
    def _incr(self, name: str) -> None:
        if self.metrics is not None:
            self.metrics.incr(name)

    def _file(self, name: str) -> str:
        return os.path.join(self.dir, name + _SUFFIX)

    def stats(self) -> dict:
        return {
            "fingerprint": self.fingerprint[:16],
            "dir": self.dir,
            "hits": self.hits,
            "misses": self.misses,
            "errors": self.errors,
            "symbol_errors": self.symbol_errors,
            "stored": self.stored,
            "live_compiles": self.live_compiles,
        }

    # -- load -----------------------------------------------------------
    def _deserialize(self, name: str, path: str) -> Any:
        with open(path, "rb") as f:
            record = pickle.load(f)
        if record.get("format") != CACHE_FORMAT:
            raise ValueError(f"cache format {record.get('format')!r} != {CACHE_FORMAT}")
        from jax.experimental import serialize_executable

        return serialize_executable.deserialize_and_load(
            record["payload"], record["in_tree"], record["out_tree"]
        )

    def preload(self) -> int:
        """Deserialize every stored executable now (the provider calls this
        while the weight stream owns the HBM bus — deserialization needs
        disk + host CPU only).  Returns the number preloaded."""
        try:
            names = [
                f[: -len(_SUFFIX)]
                for f in os.listdir(self.dir)
                if f.endswith(_SUFFIX)
            ]
        except OSError:
            return 0  # cold boot: directory appears on the first put
        for name in names:
            if name in self._preloaded:
                continue
            try:
                self._preloaded[name] = self._deserialize(name, self._file(name))
            except Exception as exc:  # noqa: BLE001 - one bad file must not kill boot
                self._note_deserialize_error(name, exc, stage="preload")
        return len(self._preloaded)

    def _note_deserialize_error(
        self, name: str, exc: BaseException, *, stage: str
    ) -> None:
        """Classify one deserialize failure, count it, discard the file.

        ``Symbols not found`` is the documented environmental mode (see
        ``symbol_errors``): a host whose shared XLA persistent
        compilation cache was already warm at STORE time serialized an
        executable without its jitted symbol definitions.  It gets a
        LOUD, named discard (``podmortem_aot_cache_symbols_lost_total``)
        and the live-compile lane re-stores a sound entry; anything else
        is generic corruption."""
        self.errors += 1
        self._incr("aot_cache_error")
        if "Symbols not found" in str(exc):
            self.symbol_errors += 1
            self._incr("aot_cache_symbols_lost")
            log.error(
                "AOT cache entry %r is missing its jitted symbol "
                "definitions (%s-time XLA 'Symbols not found'): it was "
                "serialized from a WARM shared XLA compilation cache, so "
                "the stored executable never contained its own code. "
                "Discarding it and compiling live; the re-stored entry "
                "will be self-contained.", name, stage,
            )
        else:
            log.warning(
                "AOT cache entry %r failed to deserialize during %s; "
                "falling back to live compile and discarding the file",
                name, stage, exc_info=True,
            )
        self._remove(name)

    def get(self, name: str) -> Optional[Any]:
        """The loaded executable for ``name``, or None (miss/corrupt —
        the caller compiles live)."""
        preloaded = self._preloaded.pop(name, None)
        if preloaded is not None:
            self.hits += 1
            self._incr("aot_cache_hit")
            return preloaded
        path = self._file(name)
        if not os.path.exists(path):
            self.misses += 1
            self._incr("aot_cache_miss")
            if not self._warned_cold:
                self._warned_cold = True
                log.warning(
                    "AOT executable cache MISS for %r (fingerprint %s): "
                    "compiling live and persisting for the next boot "
                    "(further misses this boot log at DEBUG)",
                    name, self.fingerprint[:16],
                )
            else:
                log.debug("AOT cache miss: %s", name)
            return None
        try:
            loaded = self._deserialize(name, path)
        except Exception as exc:  # noqa: BLE001 - corrupt entry: loud live-compile fallback
            self._note_deserialize_error(name, exc, stage="load")
            return None
        self.hits += 1
        self._incr("aot_cache_hit")
        return loaded

    def note_call_failure(self, name: str) -> None:
        """A restored executable was rejected at call time (aval/sharding
        drift the fingerprint missed): count it, drop the file so the next
        boot stores a fresh one, and let the caller compile live."""
        self.errors += 1
        self._incr("aot_cache_error")
        log.warning(
            "AOT cached executable %r rejected at call time; falling back "
            "to live compile (the stale file is discarded)", name,
        )
        self._remove(name)

    def _remove(self, name: str) -> None:
        try:
            os.remove(self._file(name))
        except OSError:
            pass

    # -- store ----------------------------------------------------------
    def put(self, name: str, compiled: Any) -> bool:
        """Serialize + persist one compiled executable (atomic rename so a
        crash mid-write can only leave a temp file, never a torn entry)."""
        try:
            from jax.experimental import serialize_executable

            payload, in_tree, out_tree = serialize_executable.serialize(compiled)
            blob = pickle.dumps({
                "format": CACHE_FORMAT,
                "name": name,
                "payload": payload,
                "in_tree": in_tree,
                "out_tree": out_tree,
            })
            os.makedirs(self.dir, exist_ok=True)
            self._write_manifest()
            fd, tmp = tempfile.mkstemp(dir=self.dir, suffix=".tmp")
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(blob)
                os.replace(tmp, self._file(name))
            except BaseException:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                raise
        except Exception:  # noqa: BLE001 - persistence is an optimisation only
            self.errors += 1
            self._incr("aot_cache_error")
            log.warning("AOT cache store failed for %r", name, exc_info=True)
            return False
        self.stored += 1
        self._incr("aot_cache_store")
        return True

    def _write_manifest(self) -> None:
        """Human-readable key anatomy next to the executables
        (docs/SERVING.md "Bring-up"): what exactly this directory is valid
        for, so a surprising miss is debuggable by diffing two manifests."""
        manifest = os.path.join(self.dir, "fingerprint.json")
        if os.path.exists(manifest):
            return
        try:
            with open(manifest, "w") as f:
                json.dump(
                    {"fingerprint": self.fingerprint, "payload": self.payload},
                    f, indent=2, sort_keys=True, default=str,
                )
        except OSError:
            pass


class CachedProgram:
    """One serving program behind the AOT cache.

    Warm: constructed with the deserialized executable and never compiles.
    Cold: the first call lowers + compiles the wrapped ``jax.jit`` function
    with its concrete arguments, persists the executable, then runs it.

    Two failure lanes, deliberately distinct:

    - a restored executable that rejects its VERY FIRST call (aval or
      sharding drift the fingerprint missed) is stale — discard the file
      loudly and compile live;
    - an executable that has already served matching calls and then sees
      different avals has a shape-POLYMORPHIC caller (the guided programs'
      automaton tables restack to new [A_pad, S_pad] shapes mid-serve) —
      that call delegates to the plain ``jax.jit``, whose trace cache
      handles the novel signature, and the executable stays for the
      canonical shape.  Executables are single-signature by construction;
      this keeps polymorphism correct without widening the cache format.
    """

    __slots__ = ("name", "_cache", "_fn", "_loaded", "_compiled", "_served")

    def __init__(self, cache: AotCache, name: str, fn: Any) -> None:
        self.name = name
        self._cache = cache
        self._fn = fn
        self._loaded = cache.get(name)
        self._compiled: Any = None
        self._served = 0

    @property
    def from_cache(self) -> bool:
        return self._loaded is not None

    def __call__(self, *args: Any) -> Any:
        exe = self._loaded if self._loaded is not None else self._compiled
        if exe is None:
            started = time.perf_counter()
            with _fresh_compile_scope():
                self._compiled = self._fn.lower(*args).compile()
            self._cache.live_compiles += 1
            log.info(
                "AOT cache: compiled %s live in %.2fs; persisting",
                self.name, time.perf_counter() - started,
            )
            self._cache.put(self.name, self._compiled)
            exe = self._compiled
        try:
            out = exe(*args)
        except Exception as err:
            # loaded executables validate input avals BEFORE donating, so
            # a rejection here leaves the arguments alive for the fallback
            if self._served == 0 and self._loaded is not None:
                self._cache.note_call_failure(self.name)
                self._loaded = None
                return self(*args)  # cold path: compile live + re-store
            if isinstance(err, (TypeError, ValueError)):
                log.debug(
                    "AOT program %s: novel arg signature; running via jit",
                    self.name,
                )
                return self._fn(*args)
            raise
        self._served += 1
        return out
