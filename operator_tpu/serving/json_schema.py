"""JSON Schema -> regex, for schema-constrained decoding (guided_json).

The serving stack constrains decoding with token-level automata compiled
from byte-level regexes (serving/regex_dfa.py).  A JSON *Schema* with
fixed structure describes a REGULAR language — every production is
finite: objects list their properties, arrays bound their lengths, and
scalars are regular — so a schema lowers to one regex and rides the
existing guided_regex machinery end to end (DFA -> token table -> decode
scan).  No new device code; ``guided_json`` is pure front-end sugar.

Supported schema subset (anything else raises ValueError at submit time,
never inside a co-batched wave):

- ``type: object`` with ``properties`` (at most 32) — emission order is
  required properties (declaration order) then optional ones; ANY subset
  of the optional properties may appear.  With a required anchor each
  optional member independently carries its own comma; an all-optional
  object enumerates one chain per starting member, which is quadratic in
  the property count — hence the 32-property cap
- ``type: string`` (optionally ``enum``/``const``; ``maxLength`` up to 64
  — the regex engine's bounded-repeat cap — and unbounded when absent,
  including with a bare ``minLength``)
- ``type: integer`` / ``number`` (optionally ``enum``/``const``)
- ``type: boolean`` / ``null``
- ``enum`` / ``const`` of scalars at any position
- ``type: array`` with ``items``; ``minItems``/``maxItems`` <= 64, and
  unbounded length when ``maxItems`` is absent
- ``anyOf`` / ``oneOf`` -> alternation
- nesting of all of the above

Deliberately NOT supported: ``$ref``/``$defs`` (recursion is not
regular), ``additionalProperties: true`` (unbounded free-form keys),
``patternProperties``, unconstrained ``object``/``array`` without
``properties``/``items``, and bare ``{"type": "json_object"}``-style
free-form JSON (nested braces need a stack; a DFA has none).

Output is COMPACT canonical JSON — no whitespace between tokens — so the
automaton stays small and generated text parses with any JSON parser.
"""

from __future__ import annotations

import json
from typing import Any

from .regex_dfa import MAX_REPEAT as _MAX_BOUND  # bounded-repeat ceiling

#: hard budget for the lowered pattern (and, checked at every recursion
#: level, for any sub-pattern): construction doubles the item regex per
#: nesting level (seq + repeat tail), so an after-the-fact check would
#: let a ~2 KB deeply-nested schema build gigabyte strings first
_PATTERN_BUDGET = 16384

# JSON string body: any char except '"', '\' and control bytes, or an
# escape sequence.  Byte-level classes, so non-ASCII rides as UTF-8.
# regex_dfa rejects \xNN escapes, so the control range is embedded as RAW
# bytes (the class parser range-matches any single byte)
_STRING_CHAR = (
    '([^"\\\\\x00-\x1f]'      # plain char (class: " \ and 0x00-0x1f excluded)
    '|\\\\["\\\\/bfnrt]'      # two-char escape: \" \\ \/ \b \f \n \r \t
    '|\\\\u[0-9a-fA-F]{4})'   # \uXXXX
)
_STRING = f'"{_STRING_CHAR}*"'
# digit counts are CAPPED (16 ~ int64 range, exponent 3): an unbounded
# \d* lets a degenerate model extend a number to max_tokens and truncate
# the document mid-match; the cap is semantically invisible and keeps
# every numeric production finite
_INTEGER = r"-?(0|[1-9]\d{0,15})"
_NUMBER = r"-?(0|[1-9]\d{0,15})(\.\d{1,15})?([eE][+-]?\d{1,3})?"
_BOOLEAN = r"(true|false)"
_NULL = r"null"

_REGEX_SPECIALS = set(".^$*+?{}[]()|\\")


def _lit(text: str) -> str:
    """Regex-escape a literal string."""
    out = []
    for ch in text:
        if ch in _REGEX_SPECIALS:
            out.append("\\" + ch)
        else:
            out.append(ch)  # json.dumps already escaped control chars
    return "".join(out)


def _scalar_literal(value: Any) -> str:
    """The regex matching exactly one JSON scalar value."""
    if value is None or isinstance(value, (bool, int, float, str)):
        try:
            # allow_nan=False: json.dumps(inf) would emit the literal
            # "Infinity", forcing output no RFC 8259 parser accepts
            return _lit(json.dumps(value, allow_nan=False))
        except ValueError:
            raise ValueError(
                f"enum/const value {value!r} has no JSON representation"
            ) from None
    raise ValueError(
        f"enum/const values must be JSON scalars, got {type(value).__name__}"
    )


def _bound(schema: dict, key: str, default: int) -> int:
    value = schema.get(key, default)
    if not isinstance(value, int) or value < 0 or value > _MAX_BOUND:
        raise ValueError(
            f"{key}={value!r} unsupported (must be an int in [0, {_MAX_BOUND}] "
            f"— the automaton's bounded-repeat cap)"
        )
    return value


def _string_regex(schema: dict) -> str:
    if "minLength" in schema or "maxLength" in schema:
        lo = _bound(schema, "minLength", 0)
        if "maxLength" not in schema:
            # minLength alone must NOT silently impose a ceiling: emit the
            # unbounded {m,} repeat (same openness as the default _STRING)
            return f'"{_STRING_CHAR}{{{lo},}}"'
        hi = _bound(schema, "maxLength", _MAX_BOUND)
        if lo > hi:
            raise ValueError(f"minLength {lo} > maxLength {hi}")
        return f'"{_STRING_CHAR}{{{lo},{hi}}}"'
    return _STRING


def _object_regex(schema: dict) -> str:
    properties = schema.get("properties")
    if not isinstance(properties, dict) or not properties:
        raise ValueError(
            "type:object needs non-empty 'properties' (free-form objects "
            "are not a regular language)"
        )
    if schema.get("additionalProperties") not in (None, False):
        raise ValueError("additionalProperties must be false/absent")
    if len(properties) > 32:
        raise ValueError(
            f"object has {len(properties)} properties; at most 32 supported "
            f"(the all-optional construction is quadratic in property count)"
        )
    required = schema.get("required")
    if required is None:
        required_set = set(properties)
    else:
        if not isinstance(required, list) or not all(
            isinstance(n, str) for n in required
        ):
            raise ValueError("'required' must be a list of property names")
        unknown = set(required) - set(properties)
        if unknown:
            raise ValueError(f"required names unknown properties: {sorted(unknown)}")
        required_set = set(required)

    def member(name: str) -> str:
        return f"{_lit(json.dumps(name))}:{_schema_regex(properties[name])}"

    # emission order: required properties (declaration order) first, then
    # optional ones — with a required anchor present, every optional
    # member carries its own leading comma and any SUBSET may appear
    required_members = [member(n) for n in properties if n in required_set]
    optional_members = [member(n) for n in properties if n not in required_set]
    if required_members:
        body = ",".join(required_members) + "".join(
            f"(,{m})?" for m in optional_members
        )
    elif optional_members:
        # no required anchor: the first present member has no comma, so
        # enumerate each "starts at member i" chain (any subset, in order)
        chains = [
            optional_members[i]
            + "".join(f"(,{m})?" for m in optional_members[i + 1:])
            for i in range(len(optional_members))
        ]
        body = "(" + "|".join(chains) + ")?"
    else:  # unreachable: properties is non-empty
        body = ""
    return "\\{" + body + "\\}"


def _array_regex(schema: dict) -> str:
    items = schema.get("items")
    if not isinstance(items, dict):
        raise ValueError(
            "type:array needs an 'items' schema (free-form arrays are not "
            "a regular language)"
        )
    lo = _bound(schema, "minItems", 0)
    item = _schema_regex(items)
    if "maxItems" not in schema:
        # no ceiling given: unbounded {m,} tail, not a silent 64 cap
        more = f"(,{item}){{{max(0, lo - 1)},}}"
    else:
        hi = _bound(schema, "maxItems", _MAX_BOUND)
        if lo > hi:
            raise ValueError(f"minItems {lo} > maxItems {hi}")
        if hi == 0:
            return r"\[\]"
        # first item + up to hi-1 comma-separated others
        more = f"(,{item}){{{max(0, lo - 1)},{hi - 1}}}"
    seq = f"{item}{more}"
    if lo == 0:
        seq = f"({seq})?"
    return r"\[" + seq + r"\]"


def _schema_regex(schema: Any) -> str:
    """Recursive lowering with the pattern budget enforced at EVERY level:
    each nesting level embeds its child pattern up to twice, so checking
    only the final string would first materialise ~2^depth bytes."""
    regex = _schema_regex_impl(schema)
    if len(regex) > _PATTERN_BUDGET:
        raise ValueError(
            f"schema lowers to a pattern above the {_PATTERN_BUDGET}-char "
            f"budget — reduce optional properties, bounds, or nesting"
        )
    return regex


def _schema_regex_impl(schema: Any) -> str:
    if not isinstance(schema, dict):
        raise ValueError(f"schema must be an object, got {type(schema).__name__}")
    for key in ("$ref", "$defs", "definitions", "patternProperties"):
        if key in schema:
            raise ValueError(f"{key} is not supported (recursion is not regular)")
    if "const" in schema:
        return _scalar_literal(schema["const"])
    if "enum" in schema:
        values = schema["enum"]
        if not isinstance(values, list) or not values:
            raise ValueError("enum must be a non-empty list")
        return "(" + "|".join(_scalar_literal(v) for v in values) + ")"
    alts = schema.get("anyOf") or schema.get("oneOf")
    if alts is not None:
        if not isinstance(alts, list) or not alts:
            raise ValueError("anyOf/oneOf must be a non-empty list")
        return "(" + "|".join(_schema_regex(s) for s in alts) + ")"
    kind = schema.get("type")
    if isinstance(kind, list):
        if not kind:
            raise ValueError("type: [] is empty")
        return "(" + "|".join(
            _schema_regex({**schema, "type": k}) for k in kind
        ) + ")"
    if kind == "object":
        return _object_regex(schema)
    if kind == "array":
        return _array_regex(schema)
    if kind == "string":
        return _string_regex(schema)
    if kind == "integer":
        return _INTEGER
    if kind == "number":
        return _NUMBER
    if kind == "boolean":
        return _BOOLEAN
    if kind == "null":
        return _NULL
    raise ValueError(
        f"unsupported schema: type={kind!r} (supported: object/array/string/"
        f"integer/number/boolean/null, enum/const, anyOf/oneOf)"
    )


def schema_to_regex(schema: "dict | str") -> str:
    """Compile a JSON Schema (dict or JSON text) to a full-match regex.

    The result feeds ``guided_regex`` unchanged: regex_dfa compiles it to
    a DFA whose token-closure table the decode scan consumes.
    """
    if isinstance(schema, str):
        try:
            schema = json.loads(schema)
        except json.JSONDecodeError as exc:
            raise ValueError(f"guided_json is not valid JSON: {exc}") from None
    # the budget is enforced at every recursion level (_schema_regex);
    # user-typed guided_regex is separately capped at 1024 chars by the
    # HTTP layer — schema-lowered patterns get this larger budget because
    # NFA + subset construction run at submit time on the serving thread
    return _schema_regex(schema)


#: input-size ceiling shared by every guided_json entry point
MAX_SCHEMA_BYTES = 8192


def lower_guided_json(schema: Any) -> str:
    """Validate + lower a user-supplied guided_json value to a regex.

    The ONE front door for both entry points — the HTTP API
    (serving/httpserver.py) and AIProvider ``additionalConfig``
    (serving/provider.py) — so input-shape checks and the schema-size cap
    can never drift between them.  Raises ValueError on anything
    unservable.
    """
    if not isinstance(schema, (dict, str)):
        raise ValueError("guided_json must be a schema object or JSON string")
    encoded = json.dumps(schema) if isinstance(schema, dict) else schema
    if len(encoded) > MAX_SCHEMA_BYTES:
        raise ValueError(
            f"guided_json schema too large (>{MAX_SCHEMA_BYTES} bytes)"
        )
    return schema_to_regex(schema)


__all__ = ["MAX_SCHEMA_BYTES", "lower_guided_json", "schema_to_regex"]
