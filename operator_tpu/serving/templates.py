"""Model-family chat templates for the chat completion endpoint.

Real instruct checkpoints are trained against a specific conversation
format; feeding them the neutral ``role: content`` fallback degrades
their output badly.  The formats below are the published conventions for
each family served from models/configs.py (no network egress is needed —
they are fixed strings, reproduced from the models' public cards):

- **llama3**: ``<|start_header_id|>role<|end_header_id|>\\n\\ncontent<|eot_id|>``
- **chatml** (Qwen2/2.5): ``<|im_start|>role\\ncontent<|im_end|>``
- **mistral**: ``[INST] ... [/INST]`` with system folded into the first
  user turn (Mistral has no system role)
- **zephyr** (TinyLlama-Chat): ``<|system|>/<|user|>/<|assistant|>`` tags
- **plain**: the neutral fallback for unknown models / base checkpoints

Templates never emit a BOS string (``<|begin_of_text|>`` / ``<s>``): the
engine's tokenizer prepends ``bos_id`` at admission (engine.py admit, all
tokenizer classes default ``add_bos=True``) — baking it into the text
would double it.

Selection is by model config name prefix (:func:`template_for`); the
serving CLI and operator pass the loaded model's name through.  The
templates emit TEXT — tokenization happens downstream, so they work with
any tokenizer that covers the special strings (a real checkpoint's
tokenizer does; the byte/BPE fallbacks encode them literally, which is
exactly as good as the neutral format was).
"""

from __future__ import annotations

from typing import Callable, Sequence

Message = dict  # {"role": str, "content": str} (content pre-flattened)


def _plain(messages: Sequence[Message]) -> str:
    parts = [f"{m.get('role', 'user')}: {m['content']}" for m in messages]
    parts.append("assistant:")
    return "\n".join(parts)


def _llama3(messages: Sequence[Message]) -> str:
    parts = []  # BOS comes from the tokenizer, not the template
    for m in messages:
        parts.append(
            f"<|start_header_id|>{m.get('role', 'user')}<|end_header_id|>\n\n"
            f"{m['content']}<|eot_id|>"
        )
    parts.append("<|start_header_id|>assistant<|end_header_id|>\n\n")
    return "".join(parts)


def _chatml(messages: Sequence[Message]) -> str:
    parts = [
        f"<|im_start|>{m.get('role', 'user')}\n{m['content']}<|im_end|>\n"
        for m in messages
    ]
    parts.append("<|im_start|>assistant\n")
    return "".join(parts)


def _mistral(messages: Sequence[Message]) -> str:
    # no system role: fold system text into the first user turn (the
    # published convention); alternating [INST] user [/INST] assistant</s>
    system = "\n".join(
        m["content"] for m in messages if m.get("role") == "system"
    )
    parts = []  # BOS comes from the tokenizer, not the template
    pending_system = system
    for m in messages:
        role = m.get("role", "user")
        if role == "system":
            continue
        if role == "assistant":
            parts.append(f" {m['content']}</s>")
        else:
            content = m["content"]
            if pending_system:
                content = f"{pending_system}\n\n{content}"
                pending_system = ""
            parts.append(f"[INST] {content} [/INST]")
    if pending_system:  # system-only conversation: never drop the content
        parts.append(f"[INST] {pending_system} [/INST]")
    return "".join(parts)


def _zephyr(messages: Sequence[Message]) -> str:
    parts = [
        f"<|{m.get('role', 'user')}|>\n{m['content']}</s>\n" for m in messages
    ]
    parts.append("<|assistant|>\n")
    return "".join(parts)


#: model-name prefix -> formatter (first match wins, checked in order)
_TEMPLATES: list[tuple[str, Callable[[Sequence[Message]], str]]] = [
    ("llama-3", _llama3),
    ("qwen", _chatml),
    ("mistral", _mistral),
    ("tinyllama", _zephyr),
]


def template_for(model_name: str) -> Callable[[Sequence[Message]], str]:
    """The chat formatter for a model config name (prefix match; the
    neutral plain format for anything unknown, incl. tiny-test)."""
    lowered = (model_name or "").lower()
    for prefix, formatter in _TEMPLATES:
        if lowered.startswith(prefix):
            return formatter
    return _plain


__all__ = ["template_for"]
