"""Serve the TPU engine over the OpenAI wire format.

    python -m operator_tpu.serving [--host 0.0.0.0] [--port 8000]

Model/weights/mesh come from the same operator config env the cluster
deployment uses (utils/config.py): OPERATOR_TPU_MODEL, CHECKPOINT_DIR,
WEIGHT_DTYPE, SERVING_MESH, MAX_BATCH_SIZE, ... plus
OPERATOR_TPU_API_TOKEN to require a bearer token.  This is the
standalone-inference face of the framework — the in-cluster operator
drives the identical engine in-process (serving/provider.py).
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--host", default=os.environ.get("OPERATOR_TPU_HOST", "0.0.0.0"))
    parser.add_argument(
        "--port", type=int, default=int(os.environ.get("OPERATOR_TPU_PORT", "8000"))
    )
    args = parser.parse_args()
    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s %(name)s %(levelname)s %(message)s"
    )

    platform = os.environ.get("OPERATOR_TPU_PLATFORM", "").strip()
    if platform:
        # only a live config update reliably pins another backend (same
        # pattern as bench.py BENCH_PLATFORM / tests/conftest.py)
        import jax

        jax.config.update("jax_platforms", platform)
    else:
        # honour plain JAX_PLATFORMS=cpu too: a sitecustomize may force
        # jax_platforms to the TPU plugin, in which case the env var alone
        # never takes effect and a dead tunnel hangs startup silently
        from ..utils.platform import pin_cpu_if_requested

        pin_cpu_if_requested()

    from .httpserver import serve_forever
    from .provider import TPUNativeProvider, build_serving_engine

    from ..utils.config import OperatorConfig

    cfg = OperatorConfig.from_env()
    engine, model_id = build_serving_engine()
    analysis_backend = TPUNativeProvider(
        engine, model_id=model_id,
        # same PREFIX_CACHE gate operator mode wires: a disabled cache
        # must not grow the registry through the analyze route
        register_template_prefixes=cfg.prefix_cache,
    )

    # /v1/embeddings: MiniLM when a checkpoint is mounted, lexical hashing
    # otherwise — the one shared ladder (patterns/semantic.py)
    from ..patterns.semantic import build_embedder

    embedder = build_embedder(os.environ.get("ENCODER_CHECKPOINT_DIR", "").strip())

    try:
        asyncio.run(
            serve_forever(
                engine,
                model_id=model_id,
                host=args.host,
                port=args.port,
                api_token=os.environ.get("OPERATOR_TPU_API_TOKEN") or None,
                embedder=embedder,
                analysis_backend=analysis_backend,
                # stable replica identity for the failover router's
                # /healthz polls: the serving Deployment injects POD_NAME
                # (downward API); hostname otherwise
                replica_id=(
                    os.environ.get("SERVING_REPLICA_ID")
                    or os.environ.get("POD_NAME")
                    or None
                ),
                # POST /profile?seconds=N (PROFILE_ENABLED / PROFILE_DIR)
                profile_enabled=cfg.profile_enabled,
                profile_dir=cfg.profile_dir,
            )
        )
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
